"""Transformer family: BERT-style encoder (MLM) and causal decoder (LM).

Reference analog: the harness's BERT-base distributed train script
(SURVEY.md §2a 'Model fns' row; BASELINE.json:10) — a raw-TF graph whose
variables `replica_device_setter` scattered over PS tasks. TPU-first
choices here:

- **bf16 compute, f32 LayerNorm/softmax**: matmuls hit the MXU in
  bfloat16; normalization statistics and attention logits stay f32.
- **Tensor parallelism by layout, not code**: parameters are plain flax
  params; `tp_rules()` returns the path-regex → PartitionSpec table
  (megatron column/row pattern) and GSPMD inserts the all-gather /
  reduce-scatter. Swapping TP degree touches zero model code
  (parallel/sharding.py design).
- **Attention dispatch**: dense oracle (ops/attention.py), Pallas flash
  kernel on TPU (ops/flash_attention.py), or sequence-parallel schedules
  (ring/ulysses/allgather, parallel/ring_attention.py) when the mesh has
  a `seq` axis — selected by config, same module code.
- **Tied embeddings**: the MLM/LM head attends the input embedding table
  (one [vocab, d_model] matrix, vocab-shardable over `model`).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..data.text import IGNORE_INDEX  # single sentinel shared with the data layer
from ..ops.attention import attention_reference, blockwise_attention
from ..ops.flash_attention import flash_attention
from ..ops.moe import collect_aux_loss
from ..parallel import mesh as mesh_lib
from ..parallel import sharding
from ..parallel.ring_attention import sequence_parallel_attention
from ..utils import flops as flops_lib


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 30528  # BERT vocab rounded up to a multiple of 128
    max_len: int = 512
    num_layers: int = 12
    d_model: int = 768
    num_heads: int = 12
    d_ff: int = 3072
    dropout: float = 0.1
    causal: bool = False         # False = bidirectional encoder (BERT)
    pre_ln: bool = False         # BERT is post-LN; decoders default pre-LN
    dtype: str = "bfloat16"
    # "auto": flash kernel on TPU, dense reference elsewhere.
    # "dense" | "blockwise" | "flash" force an implementation.
    attention_impl: str = "auto"
    # Paged-decode attention dispatch (ops.attention.paged_attention):
    # "auto" = block-table Pallas kernel on TPU, gather-free fused einsum
    # elsewhere; "gather" forces the PR-13 gather-then-attend path (the
    # exact-parity escape hatch); "fused" | "pallas" force those.
    paged_attention_impl: str = "auto"
    # None = no sequence parallelism; "ring"|"ulysses"|"allgather" engage
    # when the model is built with a mesh whose seq axis > 1.
    seq_impl: str | None = None
    # MoE: 0 = dense FFN everywhere; >0 = every `moe_every`-th block swaps
    # its FFN for a MoEMLP with this many experts (ops/moe.py; expert dim
    # shards over the `expert` mesh axis via moe_rules()).
    num_experts: int = 0
    moe_every: int = 2
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    # Fuse LayerNorm into the following matmul's prologue via the Pallas
    # kernel (ops/fused_ln_matmul.py): the normalized tensor between
    # ln1→q/k/v and ln2→mlp_in never hits HBM. Pre-LN only (post-LN's
    # LayerNorm output IS the residual stream — it must materialize), and
    # incompatible with a model-axis (TP) sharded mesh (the kernel isn't
    # shard_map-wrapped here). Same param tree as the unfused path.
    fused_ln_matmul: bool = False
    # Rematerialize each Block on the backward pass (jax.checkpoint via
    # nn.remat): activation memory drops from O(L) blocks to O(1) at the
    # cost of one extra forward — the TPU-native descendant of TF's
    # recompute_grad, and the standard lever for long-sequence/large-batch
    # HBM pressure (task brief: trade FLOPs for memory).
    remat: bool = False
    # Project q/k/v with ONE [d, 3·d] matmul ("qkv") instead of three
    # [d, d] matmuls — one larger MXU call, one read of the residual
    # stream instead of three (megatron-style fused QKV). GSPMD path
    # only: incompatible with fused_ln_matmul (which owns its own
    # projections) and with manual TP islands (tp_shards > 1); GSPMD TP
    # shards the fused kernel columns via tp_rules and reshards to heads.
    # Param tree differs from the unfused layout (qkv/{kernel,bias}).
    fused_qkv: bool = False
    # >0: causal-LM training loss runs the vocab projection + xent per
    # sequence chunk of this size (chunked_lm_loss_fn) so the [B, S,
    # vocab] logits tensor never materializes — required for large-vocab
    # LMs at real batch sizes (13 GB f32 at B=128, S=512, V=50304).
    # 0 = dense loss. Identical math either way (parity-tested).
    xent_chunk: int = 0
    # Input dtype of the tied-embedding vocab projection. "float32"
    # (default) is the exact path; "bfloat16" runs the head matmul on
    # the fast MXU tier with f32 accumulation — the standard LLM head
    # recipe. At GPT-2 shapes the head is ~25-30% of model FLOPs and an
    # f32 matmul runs at ~1/4 the bf16 MXU rate, so this is a large
    # lever for causal LMs; softmax/xent always run in f32 regardless.
    head_dtype: str = "float32"

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.num_heads == 0
        return self.d_model // self.num_heads


def bert_base() -> TransformerConfig:
    """BERT-base/uncased shape (BASELINE.json:10)."""
    return TransformerConfig()


def gpt_small(causal_len: int = 1024) -> TransformerConfig:
    """Decoder-only LM, GPT-2-small shape — pre-LN, causal."""
    return TransformerConfig(
        vocab_size=50304, max_len=causal_len, num_layers=12, d_model=768,
        num_heads=12, d_ff=3072, causal=True, pre_ln=True,
    )


# ---------------------------------------------------------------------------
# Tensor-parallel layout (megatron column/row pattern) — the rules table
# ---------------------------------------------------------------------------

#: Static param-path coverage fixture for TRANSFORMER_RULES: the UNION of
#: the three shipped tree variants at num_layers=2 — BERT encoder
#: (post-LN, split q/k/v, dense MLP), causal pre-LN decoder with
#: fused_qkv, and the MoE interleave (num_experts>0, moe_every=2).
#: tests/test_sharding.py::test_transformer_coverage_fixture_is_live
#: regenerates this union from the real models and pins it; the dtflint
#: shard-rules-coverage rule re-checks totality/liveness against it on
#: every lint run.
#: (fully literal — the dtflint shard-rules-coverage rule reads it
#: statically, so no comprehension/format indirection)
_TRANSFORMER_COVERAGE = (
    "embed_ln/bias", "embed_ln/scale", "final_ln/bias", "final_ln/scale",
    "layer_0/attn/attn_out/bias", "layer_0/attn/attn_out/kernel",
    "layer_0/attn/key/bias", "layer_0/attn/key/kernel",
    "layer_0/attn/qkv/bias", "layer_0/attn/qkv/kernel",
    "layer_0/attn/query/bias", "layer_0/attn/query/kernel",
    "layer_0/attn/value/bias", "layer_0/attn/value/kernel",
    "layer_0/ln1/bias", "layer_0/ln1/scale", "layer_0/ln2/bias",
    "layer_0/ln2/scale", "layer_0/mlp_in/bias", "layer_0/mlp_in/kernel",
    "layer_0/mlp_out/bias", "layer_0/mlp_out/kernel",
    "layer_1/attn/attn_out/bias", "layer_1/attn/attn_out/kernel",
    "layer_1/attn/key/bias", "layer_1/attn/key/kernel",
    "layer_1/attn/qkv/bias", "layer_1/attn/qkv/kernel",
    "layer_1/attn/query/bias", "layer_1/attn/query/kernel",
    "layer_1/attn/value/bias", "layer_1/attn/value/kernel",
    "layer_1/ln1/bias", "layer_1/ln1/scale", "layer_1/ln2/bias",
    "layer_1/ln2/scale", "layer_1/mlp_in/bias", "layer_1/mlp_in/kernel",
    "layer_1/mlp_out/bias", "layer_1/mlp_out/kernel", "layer_1/moe/b_in",
    "layer_1/moe/b_out", "layer_1/moe/router/bias",
    "layer_1/moe/router/kernel", "layer_1/moe/w_in", "layer_1/moe/w_out",
    "mlm_bias", "mlm_ln/bias", "mlm_ln/scale", "mlm_transform/bias",
    "mlm_transform/kernel", "pos_embed", "tok_embed/embedding",
)

#: The Transformer family's partition-rules table (parallel/sharding.py
#: engine; docs/parallelism.md "Authoring partition-rules tables").
#: Column-parallel in (output dim over `model`), row-parallel out (input
#: dim over `model`) — one all-reduce per block half, placed by GSPMD on
#: ICI. Variant-conditional rows carry tags; ``transformer_rules(cfg)``
#: selects the exact table for a config, so a dead row (or a param the
#: table forgot) is a hard PartitionCoverageError, not a silent layout.
#: The four MoE rows mirror ops.moe.moe_rules() (pinned by
#: tests/test_sharding.py::test_transformer_moe_rows_mirror_moe_rules).
TRANSFORMER_RULES = sharding.partition_rules(
    "transformer",
    (
        # MoE experts first: "moe/w_in" must not fall through to the
        # dense "mlp_in" patterns (first-match precedence)
        (r"(^|/)w_in$", P(mesh_lib.EXPERT, None, mesh_lib.MODEL), "moe"),
        (r"(^|/)b_in$", P(mesh_lib.EXPERT, mesh_lib.MODEL), "moe"),
        (r"(^|/)w_out$", P(mesh_lib.EXPERT, mesh_lib.MODEL, None), "moe"),
        (r"(^|/)b_out$", P(mesh_lib.EXPERT, None), "moe"),
        (r"(query|key|value)/kernel", P(None, mesh_lib.MODEL), "split_qkv"),
        (r"(query|key|value)/bias", P(mesh_lib.MODEL), "split_qkv"),
        (r"qkv/kernel", P(None, mesh_lib.MODEL), "fused_qkv"),
        (r"qkv/bias", P(mesh_lib.MODEL), "fused_qkv"),
        (r"attn_out/kernel", P(mesh_lib.MODEL, None)),
        (r"mlp_in/kernel", P(None, mesh_lib.MODEL), "dense_mlp"),
        (r"mlp_in/bias", P(mesh_lib.MODEL), "dense_mlp"),
        (r"mlp_out/kernel", P(mesh_lib.MODEL, None), "dense_mlp"),
        (r"tok_embed/embedding", P(mesh_lib.MODEL, None)),  # vocab-sharded
        (r"mlm_bias", P(mesh_lib.MODEL)),
        # everything else (LayerNorms, pos_embed, biases of row-parallel
        # projections, the MoE router) is DECLARED replicated
        (sharding.CATCH_ALL, sharding.REPLICATED),
    ),
    coverage=_TRANSFORMER_COVERAGE,
)


def transformer_rules(cfg: TransformerConfig) -> sharding.PartitionRules:
    """The exact rules table for ``cfg``'s param tree: variant rows
    (split vs fused QKV, MoE experts, dense MLP) selected so that
    match_partition_rules' dead-rule check holds — a config/table
    mismatch fails loudly with the full attribution listing."""
    tags = ["fused_qkv" if cfg.fused_qkv else "split_qkv"]
    n_moe = sum(
        1 for i in range(cfg.num_layers)
        if cfg.num_experts > 0 and i % cfg.moe_every == cfg.moe_every - 1
    )
    if n_moe:
        tags.append("moe")
    if n_moe < cfg.num_layers:
        tags.append("dense_mlp")
    return TRANSFORMER_RULES.select(*tags)


def tp_rules():
    """Legacy soft form of :data:`TRANSFORMER_RULES` (every variant row,
    replicate-on-miss semantics) — kept for ad-hoc trees and the
    pre-engine call sites; shipped workloads use
    :func:`transformer_rules`."""
    return TRANSFORMER_RULES.as_path_rules()


# ---------------------------------------------------------------------------
# Modules
# ---------------------------------------------------------------------------


class _LNParams(nn.Module):
    """LayerNorm scale/bias params only (flax naming) — the fused
    ln_matmul path owns the math, this scope owns the tree."""

    features: int

    @nn.compact
    def __call__(self):
        return (
            self.param("scale", nn.initializers.ones, (self.features,)),
            self.param("bias", nn.initializers.zeros, (self.features,)),
        )


class _DenseParams(nn.Module):
    """nn.Dense-compatible kernel/bias params (same shapes, inits, tree)."""

    features: int
    in_features: int

    @nn.compact
    def __call__(self):
        return (
            self.param("kernel", nn.initializers.normal(0.02),
                       (self.in_features, self.features)),
            self.param("bias", nn.initializers.zeros, (self.features,)),
        )


def _row_parallel_dense(h, out_features, in_features_local, name, dtype,
                        parent):
    """Megatron row-parallel projection inside a shard_map island: local
    [in_local, out] slice computes a partial sum, psum over `model`
    reduces it, the (replicated) bias is added ONCE after the reduce.
    Shared by attn_out and mlp_out so the two cannot drift."""
    w, b = _DenseParams(out_features, in_features_local, name=name,
                        parent=parent)()
    y = jnp.dot(h, w.astype(dtype), preferred_element_type=jnp.float32)
    y = jax.lax.psum(y, mesh_lib.MODEL)
    return (y + b).astype(dtype)


class SelfAttention(nn.Module):
    cfg: TransformerConfig
    mesh: Any = None  # jax.sharding.Mesh or None; static module metadata
    # >1 = MANUAL megatron tensor parallelism for shard_map islands (the
    # pipelined path): this instance sees LOCAL column slices of q/k/v
    # (H/tp heads) and a LOCAL row slice of attn_out, and reduces the
    # out-projection with an explicit psum over the `model` axis. Mutually
    # exclusive with GSPMD TP (tp_rules), which shards the SAME math from
    # outside jit. Param tree paths/full shapes are identical either way.
    tp_shards: int = 1

    @nn.compact
    def __call__(self, x, mask, *, train: bool, ln_params=None,
                 cache=None, decode_pos=None):
        # ``cache`` = {"k","v"} [B,H,M,D] per-layer KV buffers (serve/
        # kv_cache.py) and ``decode_pos`` [B,S] the absolute positions of
        # the S incoming tokens: the new k/v are written at those offsets
        # and attention runs over the updated buffers via the masked dense
        # path (ops.attention.cached_attention) — returns (out, new_cache).
        cfg = self.cfg
        dtype = jnp.dtype(cfg.dtype)
        if cache is not None and self.tp_shards > 1:
            raise ValueError(
                "KV-cached decode supports GSPMD TP only; manual TP "
                "islands (tp_shards > 1) hold local head slices the "
                "cache layout does not model"
            )
        if cfg.num_heads % self.tp_shards:
            raise ValueError(
                f"num_heads={cfg.num_heads} not divisible by "
                f"tp_shards={self.tp_shards}"
            )
        if self.tp_shards > 1 and ln_params is not None:
            raise ValueError(
                "fused_ln_matmul is incompatible with manual TP islands"
            )
        if cfg.fused_qkv and ln_params is not None:
            raise ValueError(
                "fused_qkv and fused_ln_matmul are mutually exclusive "
                "(the LN+matmul kernel owns its own per-projection path)"
            )
        H, D = cfg.num_heads // self.tp_shards, cfg.head_dim
        B, S, _ = x.shape
        # [B,S,Hd] -> [B,H,S,D] (ops/ layout convention)
        split = lambda t: t.reshape(B, S, H, D).transpose(0, 2, 1, 3)
        if ln_params is not None:
            # fused path: x is the RAW residual stream; q/k/v matmuls
            # apply the block's LayerNorm in their kernel prologue
            from ..ops.fused_ln_matmul import ln_matmul

            ls, lb = ln_params
            x2 = x.reshape(B * S, cfg.d_model)

            def proj(name):
                w, b = _DenseParams(H * D, cfg.d_model, name=name)()
                return ln_matmul(
                    x2, ls, lb, w.astype(dtype), b, out_dtype=dtype
                ).reshape(B, S, H * D)

            q = split(proj("query"))
            k = split(proj("key"))
            v = split(proj("value"))
        elif cfg.fused_qkv:
            if self.tp_shards > 1:
                raise ValueError(
                    "fused_qkv is incompatible with manual TP islands "
                    "(tp_shards > 1); use the GSPMD tp_rules path")
            # Column order is HEAD-major ([d] -> [H, 3, D]), not
            # projection-major ([3, H, D]): under GSPMD TP the kernel's
            # column axis shards contiguously over `model`, and head-major
            # grouping puts each shard's columns at whole-head boundaries
            # (q_h/k_h/v_h co-located), so the q/k/v extraction below is
            # shard-local — projection-major would straddle the q|k|v
            # boundaries and force a per-layer reshard.
            qkv = nn.Dense(
                3 * H * D, dtype=dtype, name="qkv",
                kernel_init=nn.initializers.normal(0.02),
            )(x).reshape(B, S, H, 3, D)
            q = qkv[..., 0, :].transpose(0, 2, 1, 3)  # [B,H,S,D]
            k = qkv[..., 1, :].transpose(0, 2, 1, 3)
            v = qkv[..., 2, :].transpose(0, 2, 1, 3)
        else:
            dense = lambda name: nn.Dense(
                H * D, dtype=dtype, name=name,
                kernel_init=nn.initializers.normal(0.02),
            )
            q = split(dense("query")(x))
            k = split(dense("key")(x))
            v = split(dense("value")(x))

        new_cache = None
        seq_shards = self.mesh.shape[mesh_lib.SEQ] if self.mesh is not None else 1
        if cache is not None and "bt" in cache:
            from ..ops.attention import paged_append_kv, paged_attention

            # paged path: per-layer pool [NB,H,bs,D] + block table [B,MB].
            # New K/V scatter through the table at the tokens' absolute
            # positions (sentinel ids drop padded/idle writes); attention
            # reads the pool through the table via the impl-selected
            # dispatch — fused/Pallas by default, or the gather-then-
            # attend masked dense form as the exact-parity escape hatch.
            bt = cache["bt"]
            ck = paged_append_kv(cache["k"], k, bt, decode_pos)
            cv = paged_append_kv(cache["v"], v, bt, decode_pos)
            new_cache = {"k": ck, "v": cv, "bt": bt}
            out = paged_attention(
                q, ck, cv, bt, q_pos=decode_pos,
                impl=cfg.paged_attention_impl,
            )
        elif cache is not None:
            from ..ops.attention import append_kv, cached_attention

            start = decode_pos[:, 0]
            ck = append_kv(cache["k"], k, start)
            cv = append_kv(cache["v"], v, start)
            new_cache = {"k": ck, "v": cv}
            # masked full attention over the cache: the flash kernel does
            # not apply at Sq=1 / per-sequence offsets (see cached_attention)
            out = cached_attention(q, ck, cv, q_pos=decode_pos)
        elif cfg.seq_impl is not None and seq_shards > 1:
            out = sequence_parallel_attention(
                q, k, v, self.mesh, impl=cfg.seq_impl,
                causal=cfg.causal, kv_mask=mask,
            )
        else:
            impl = cfg.attention_impl
            if impl == "auto":
                impl = "flash" if jax.default_backend() == "tpu" else "dense"
            if impl == "flash":
                # pad S to the kernel's block multiple; padded keys masked out,
                # padded query rows sliced off (flash_attention requires
                # Sq/Sk % block == 0)
                pad = (-S) % 128 if S > 128 else 0
                if pad:
                    pq, pk, pv = (
                        jnp.pad(t, ((0, 0), (0, 0), (0, pad), (0, 0)))
                        for t in (q, k, v)
                    )
                    pmask = (
                        mask
                        if mask is not None
                        else jnp.ones((B, S), bool)
                    )
                    pmask = jnp.pad(pmask, ((0, 0), (0, pad)))
                    out = flash_attention(
                        pq, pk, pv, causal=cfg.causal, kv_mask=pmask
                    )[:, :, :S]
                else:
                    out = flash_attention(q, k, v, causal=cfg.causal, kv_mask=mask)
            elif impl == "blockwise":
                out = blockwise_attention(q, k, v, causal=cfg.causal, kv_mask=mask)
            else:
                out = attention_reference(q, k, v, causal=cfg.causal, kv_mask=mask)

        out = out.transpose(0, 2, 1, 3).reshape(B, S, H * D)
        if self.tp_shards > 1:
            # _DenseParams keeps the exact nn.Dense param tree
            # ('attn_out/{kernel,bias}')
            out = _row_parallel_dense(out, cfg.d_model, H * D, "attn_out",
                                      dtype, self)
        else:
            out = nn.Dense(cfg.d_model, dtype=dtype, name="attn_out",
                           kernel_init=nn.initializers.normal(0.02))(out)
        out = nn.Dropout(cfg.dropout, deterministic=not train)(out)
        return out if cache is None else (out, new_cache)


class Block(nn.Module):
    cfg: TransformerConfig
    mesh: Any = None
    use_moe: bool = False
    # Manual megatron TP inside a shard_map island (see SelfAttention.
    # tp_shards): column-parallel q/k/v + mlp_in (local out slices),
    # row-parallel attn_out + mlp_out (psum over `model`, bias once).
    # LayerNorms see the full d_model (never sharded). The pipelined path
    # sets this from the mesh; the GSPMD path must leave it at 1.
    tp_shards: int = 1

    @nn.compact
    def __call__(self, x, mask, train: bool, cache=None, decode_pos=None):
        # ``train`` is positional (not kw-only) so nn.remat can mark it
        # static (static_argnums counts the module itself as arg 0) —
        # but deliberately has no default: every call site must decide.
        # ``cache``/``decode_pos``: KV-cached decode (see SelfAttention);
        # the return becomes (x, new_cache). Never combined with remat —
        # decode is forward-only.
        cfg = self.cfg
        dtype = jnp.dtype(cfg.dtype)
        tp = self.tp_shards
        if tp > 1 and self.use_moe:
            raise ValueError("manual TP islands don't support MoE blocks")
        if tp > 1 and cfg.d_ff % tp:
            raise ValueError(f"d_ff={cfg.d_ff} not divisible by tp={tp}")
        ln = lambda name: nn.LayerNorm(dtype=jnp.float32, name=name)
        base_attn = SelfAttention(cfg, self.mesh, tp_shards=tp, name="attn")

        new_cache = [None]  # box: closed over by the three attn call sites

        def attn(h, **kw):
            if cache is None:
                return base_attn(h, mask, train=train, **kw)
            y, new_cache[0] = base_attn(
                h, mask, train=train, cache=cache, decode_pos=decode_pos,
                **kw,
            )
            return y

        if self.use_moe:
            from ..ops.moe import MoEConfig, MoEMLP

            moe = MoEMLP(
                MoEConfig(
                    num_experts=cfg.num_experts, d_model=cfg.d_model,
                    d_ff=cfg.d_ff, top_k=cfg.moe_top_k,
                    capacity_factor=cfg.moe_capacity_factor, dtype=cfg.dtype,
                ),
                name="moe",
            )

            def mlp(h):
                h = moe(h, train=train)
                return nn.Dropout(cfg.dropout, deterministic=not train)(h)

            mlp_tail = None
        else:

            def mlp_tail(h):
                # everything after the mlp_in matmul — shared by the
                # plain and fused-LN paths so they cannot drift
                h = nn.gelu(h)
                if tp > 1:
                    h = _row_parallel_dense(h, cfg.d_model, cfg.d_ff // tp,
                                            "mlp_out", dtype, self)
                else:
                    h = nn.Dense(cfg.d_model, dtype=dtype, name="mlp_out",
                                 kernel_init=nn.initializers.normal(0.02))(h)
                return nn.Dropout(cfg.dropout, deterministic=not train)(h)

            def mlp(h):
                # column-parallel under tp: local d_ff/tp out slice
                h = nn.Dense(cfg.d_ff // tp, dtype=dtype, name="mlp_in",
                             kernel_init=nn.initializers.normal(0.02))(h)
                return mlp_tail(h)

        use_fused_ln = cfg.fused_ln_matmul and not self.use_moe
        if use_fused_ln:
            if tp > 1:
                raise ValueError(
                    "fused_ln_matmul is incompatible with manual TP islands"
                )
            if not cfg.pre_ln:
                raise ValueError(
                    "fused_ln_matmul requires pre_ln=True (a post-LN "
                    "LayerNorm output is the residual stream itself and "
                    "must materialize)"
                )
            if self.mesh is not None and self.mesh.shape.get(
                    mesh_lib.MODEL, 1) > 1:
                raise ValueError(
                    "fused_ln_matmul is incompatible with a model-axis "
                    "(TP) sharded mesh; disable one of the two"
                )
            from ..ops.fused_ln_matmul import ln_matmul

            B, S, d = x.shape
            ln1 = _LNParams(d, name="ln1")()
            x = x + attn(x, ln_params=ln1)
            ls2, lb2 = _LNParams(d, name="ln2")()
            wi, bi = _DenseParams(cfg.d_ff, d, name="mlp_in")()
            h = ln_matmul(
                x.reshape(B * S, d), ls2, lb2, wi.astype(dtype), bi,
                out_dtype=dtype,
            ).reshape(B, S, cfg.d_ff)
            x = x + mlp_tail(h)
        elif cfg.pre_ln:
            x = x + attn(ln("ln1")(x).astype(dtype))
            x = x + mlp(ln("ln2")(x).astype(dtype))
        else:  # post-LN (BERT)
            x = ln("ln1")(x + attn(x)).astype(dtype)
            x = ln("ln2")(x + mlp(x)).astype(dtype)
        return x if cache is None else (x, new_cache[0])


class Transformer(nn.Module):
    """Token-in, logits-out transformer. ``input_ids`` [B,S] int32;
    ``attention_mask`` [B,S] (1 = real token) or None. Returns [B,S,vocab]
    logits (f32) from the tied embedding head.

    ``positions`` [B,K] (MLM only): gather the K prediction positions
    AFTER the block stack and run the MLM head + vocab projection on
    [B,K,d] instead of [B,S,d] — the standard BERT masked-position
    optimization (the reference fed `masked_lm_positions` the same way).
    At seq 512 / K=76 this cuts the head+logits term ~6.7x; the [B,S,V]
    logits tensor (16 GiB f32 at batch 256, vocab 30K) was the dominant
    memory term in the pipelined BERT step (tools/pipeline_memory_
    analysis.py), not the schedule. Returns [B,K,vocab] logits.
    """

    cfg: TransformerConfig
    mesh: Any = None

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, *,
                 train: bool = False, positions=None,
                 return_hidden: bool = False,
                 kv_cache=None, decode_pos=None, block_table=None):
        # ``kv_cache`` (serve.kv_cache.KVCache: k/v [L,B,H,M,D]) with
        # ``decode_pos`` [B,S] switches on the serving path: the S incoming
        # tokens sit at those ABSOLUTE positions (prefill: arange(P);
        # decode: the per-sequence write index, S=1), attention runs over
        # the per-layer cache buffers, and the return is (logits, new
        # kv_cache). Causal models only; ``attention_mask`` is rejected —
        # validity is the contiguous-fill predicate (ops.cached_attention).
        # With ``block_table`` [B, max_blocks] the cache is instead a
        # paged block POOL (serve.kv_cache.PagedKVCache: k/v
        # [L, num_blocks, H, block_size, D]): K/V scatter through the
        # table and attention gathers the logical view back
        # (ops.paged_append_kv / paged_gather_kv).
        cfg = self.cfg
        dtype = jnp.dtype(cfg.dtype)
        B, S = input_ids.shape
        if block_table is not None and kv_cache is None:
            raise ValueError("block_table requires kv_cache (a block pool)")
        if kv_cache is not None:
            if not cfg.causal:
                raise ValueError("KV-cached decode requires causal=True")
            if decode_pos is None:
                raise ValueError("kv_cache requires decode_pos [B,S]")
            if attention_mask is not None:
                raise ValueError(
                    "attention_mask cannot be honored on the kv_cache "
                    "path: validity is the contiguous-fill predicate "
                    "(ops.cached_attention), which assumes real tokens "
                    "start at slot position 0 — left-padded prompts "
                    "would silently attend to garbage"
                )
            if train:
                raise ValueError("KV-cached decode is inference-only")
            if return_hidden:
                raise ValueError(
                    "kv_cache with return_hidden would drop the updated "
                    "cache (the hidden-state early return predates the "
                    "(logits, new_cache) contract)"
                )
        tok = nn.Embed(cfg.vocab_size, cfg.d_model, name="tok_embed",
                       embedding_init=nn.initializers.normal(0.02))
        x = tok(input_ids)
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02),
            (cfg.max_len, cfg.d_model), jnp.float32,
        )
        if kv_cache is not None:
            # per-sequence absolute positions (clip guards the padded tail
            # of a bucketed prefill, whose rows are discarded anyway)
            x = (x + pos[jnp.clip(decode_pos, 0, cfg.max_len - 1)]
                 ).astype(dtype)
        else:
            x = (x + pos[None, :S]).astype(dtype)
        if not cfg.pre_ln:
            x = nn.LayerNorm(dtype=jnp.float32, name="embed_ln")(x).astype(dtype)
        x = nn.Dropout(cfg.dropout, deterministic=not train)(x)

        mask = attention_mask.astype(bool) if attention_mask is not None else None
        # nn.remat-ed blocks recompute their forward during backward:
        # O(1)-block activation memory (cfg.remat docstring). argnums:
        # 0 = module, 1 = x, 2 = mask, 3 = train (static python bool).
        block_cls = (
            nn.remat(Block, static_argnums=(3,))
            if cfg.remat and kv_cache is None else Block
        )
        new_k, new_v = [], []
        for i in range(cfg.num_layers):
            use_moe = (
                cfg.num_experts > 0 and i % cfg.moe_every == cfg.moe_every - 1
            )
            block = block_cls(cfg, self.mesh, use_moe, name=f"layer_{i}")
            if kv_cache is not None:
                layer_cache = {"k": kv_cache.k[i], "v": kv_cache.v[i]}
                if block_table is not None:
                    layer_cache["bt"] = block_table
                x, lc = block(
                    x, mask, train,
                    cache=layer_cache,
                    decode_pos=decode_pos,
                )
                new_k.append(lc["k"])
                new_v.append(lc["v"])
            else:
                x = block(x, mask, train)
        if cfg.pre_ln:
            x = nn.LayerNorm(dtype=jnp.float32, name="final_ln")(x).astype(dtype)

        if return_hidden:
            # skip the vocab head: chunked losses (chunked_lm_loss_fn)
            # apply the SAME tied-embedding projection per sequence chunk
            # so the [B, S, vocab] logits tensor never materializes
            return x

        if positions is not None:
            if cfg.causal:
                raise ValueError(
                    "positions gather is the MLM head path; causal LMs "
                    "predict every position"
                )
            x = jnp.take_along_axis(
                x, positions[..., None].astype(jnp.int32), axis=1
            )  # [B, K, d]
        if not cfg.causal:
            # BERT MLM transform head before the tied projection
            x = nn.Dense(cfg.d_model, dtype=dtype, name="mlm_transform",
                         kernel_init=nn.initializers.normal(0.02))(x)
            x = nn.gelu(x)
            x = nn.LayerNorm(dtype=jnp.float32, name="mlm_ln")(x).astype(dtype)
        logits = _head_projection(x, tok.embedding, cfg.head_dtype)
        bias = self.param("mlm_bias", nn.initializers.zeros,
                          (cfg.vocab_size,), jnp.float32)
        if kv_cache is not None:
            # same dataclass type in, same out — no serve/ import here,
            # so models/ stays independent of the serving subsystem
            new_cache = dataclasses.replace(
                kv_cache, k=jnp.stack(new_k), v=jnp.stack(new_v)
            )
            return logits + bias, new_cache
        return logits + bias


# ---------------------------------------------------------------------------
# Pipeline-parallel path (parallel/pipeline.py): same family, pipe layout
# ---------------------------------------------------------------------------
#
# The flax param tree keeps one subtree per layer (layer_0..layer_{L-1});
# the SPMD pipeline schedule instead wants every block leaf stacked with a
# leading [n_stages, layers_per_stage] dim sharded P('pipe'). The two
# layouts are pure transposes of each other (to/from_pipeline_params — an
# exact round trip, so dense checkpoints load into the pipelined layout and
# back). The stage function applies the SAME ``Block`` module that the
# dense ``Transformer.__call__`` uses, so the math is shared by
# construction — no twin implementation. Constraints: homogeneous blocks
# only (no MoE interleave — MoE layers break the stacked layout). Dropout
# works through the schedule (pipelined_apply train=True + rng: per-
# (microbatch, global-layer) keys threaded through the tick, schedule-
# independent by construction — VERDICT r2 item 7).


def _layer_keys(cfg: TransformerConfig) -> list[str]:
    return [f"layer_{i}" for i in range(cfg.num_layers)]


def _check_pipelineable(cfg: TransformerConfig, n_stages: int,
                        n_virtual: int = 1) -> None:
    if cfg.num_experts > 0:
        raise ValueError(
            "pipelined Transformer requires homogeneous blocks; "
            "num_experts > 0 interleaves MoE layers (stack would be ragged)"
        )
    if cfg.num_layers % (n_stages * n_virtual):
        raise ValueError(
            f"num_layers={cfg.num_layers} not divisible by "
            f"n_stages*n_virtual={n_stages}*{n_virtual}"
        )


def to_pipeline_params(params: Any, cfg: TransformerConfig, n_stages: int,
                       n_virtual: int = 1):
    """Dense flax tree -> {"ends": non-block params, "blocks": every leaf
    [n_stages, layers_per_stage, ...]}. With ``n_virtual`` > 1 the layout
    is [n_stages, n_virtual, layers_per_chunk, ...]: device d's v-th
    chunk is the contiguous layer range of global chunk v·S+d (the
    interleaved schedule of parallel/pipeline.py)."""
    _check_pipelineable(cfg, n_stages, n_virtual)
    layers = [params[k] for k in _layer_keys(cfg)]
    blocks = jax.tree.map(lambda *xs: jnp.stack(xs), *layers)
    S, V = n_stages, n_virtual
    lc = cfg.num_layers // (S * V)
    if V == 1:
        blocks = jax.tree.map(
            lambda x: x.reshape(S, lc, *x.shape[1:]), blocks
        )
    else:
        # [L, ...] -> chunks [V, S, lc, ...] (chunk c = v*S + d) -> [S, V, lc]
        blocks = jax.tree.map(
            lambda x: x.reshape(V, S, lc, *x.shape[1:]).swapaxes(0, 1),
            blocks,
        )
    ends = {k: v for k, v in params.items() if not k.startswith("layer_")}
    return {"ends": ends, "blocks": blocks}


def from_pipeline_params(pparams: Any, cfg: TransformerConfig,
                         n_virtual: int = 1):
    """Inverse of :func:`to_pipeline_params` (for eval/checkpoint interop
    with the dense family)."""
    if n_virtual == 1:
        blocks = jax.tree.map(
            lambda x: x.reshape(x.shape[0] * x.shape[1], *x.shape[2:]),
            pparams["blocks"],
        )
    else:
        blocks = jax.tree.map(
            lambda x: x.swapaxes(0, 1).reshape(
                x.shape[0] * x.shape[1] * x.shape[2], *x.shape[3:]
            ),
            pparams["blocks"],
        )
    out = dict(pparams["ends"])
    for i, k in enumerate(_layer_keys(cfg)):
        out[k] = jax.tree.map(lambda x: x[i], blocks)
    return out


def pipeline_param_specs(pparams: Any, *, tp: bool = False) -> Any:
    """blocks → P('pipe', ...); ends pipe-replicated (FSDP on the ends is
    out of scope for the PP path).

    ``tp=True`` additionally places the `model` axis on each stacked block
    leaf — the megatron layout of TRANSFORMER_RULES shifted past the
    leading [n_stages(, n_virtual), layers_per_stage] stacking dims:
    column-parallel kernels/biases (query/key/value/mlp_in) shard their
    LAST dim, row-parallel kernels (attn_out/mlp_out) their
    second-to-last, and row-parallel biases + LayerNorms stay
    replicated. Must match ``Block(tp_shards=...)``'s local-slice
    expectations exactly. Spec construction itself lives at the seam
    (sharding.stacked_stage_specs)."""
    blocks = sharding.stacked_stage_specs(
        pparams["blocks"],
        col=r"(query|key|value|mlp_in)/(kernel|bias)$" if tp else None,
        row=r"(attn_out|mlp_out)/kernel$" if tp else None,
    )
    return {
        "ends": sharding.replicated_specs(pparams["ends"]),
        "blocks": blocks,
    }


def pipelined_apply(
    pparams: Any,
    input_ids: jax.Array,
    attention_mask: jax.Array | None,
    cfg: TransformerConfig,
    mesh: Any,
    n_microbatches: int,
    n_virtual: int = 1,
    train: bool = False,
    rng: jax.Array | None = None,
    positions: jax.Array | None = None,
) -> jax.Array:
    """input_ids [B,S] -> logits [B,S,vocab] (f32, pipe-replicated), same
    math as ``Transformer.apply(...)`` with blocks run through the
    parallel/pipeline.py microbatch schedule. With ``positions`` [B,K]
    (MLM gathered head, see ``Transformer.__call__``), the head runs on
    the gathered positions OUTSIDE the pipeline island and the return is
    [B,K,vocab].

    ``train=True`` with ``rng`` enables dropout (training-semantics parity
    with the dense path, VERDICT r2 item 7): each layer's mask key is
    ``fold_in(fold_in(rng, microbatch), global_layer_index)`` plus, inside
    a pipe>1 island, the (data, fsdp) shard index — flax draws masks at
    the LOCAL shape there, so the shard fold keeps dropout decorrelated
    across batch shards. Keys derive from schedule-independent identities,
    so any S>1 (S, V) decomposition at a fixed batch sharding draws the
    SAME masks (asserted in tests/test_pipeline.py::
    test_pipelined_dropout_schedule_independent). The pipe=1 degenerate
    path draws global-shape masks (a different but equally deterministic
    stream), and the dense path's flax-internal derivation differs again —
    exact dense-vs-pipelined parity holds at ``train=False`` only.
    """
    from ..parallel.pipeline import microbatch, pipeline_apply, unmicrobatch

    use_dropout = train and cfg.dropout > 0.0
    if use_dropout and rng is None:
        raise ValueError("train=True with cfg.dropout > 0 requires rng")
    dtype = jnp.dtype(cfg.dtype)
    ends = pparams["ends"]
    B, S = input_ids.shape
    embed_tbl = ends["tok_embed"]["embedding"]
    x = embed_tbl[input_ids] + ends["pos_embed"][None, :S]
    x = x.astype(dtype)
    if not cfg.pre_ln:
        x = nn.LayerNorm(dtype=jnp.float32).apply(
            {"params": ends["embed_ln"]}, x
        ).astype(dtype)
    if use_dropout:
        # the dense path's embedding dropout (Transformer.__call__), done
        # outside the pipeline island; num_layers offsets it past every
        # global layer index used below
        keep = 1.0 - cfg.dropout
        ekey = jax.random.fold_in(rng, cfg.num_layers)
        x = x * jax.random.bernoulli(
            ekey, keep, x.shape).astype(x.dtype) / keep

    stage_cfg = dataclasses.replace(
        cfg, dropout=cfg.dropout if use_dropout else 0.0, seq_impl=None)
    # PP×TP: a model axis on the mesh turns on manual megatron TP inside
    # the island — each device holds [pipe-slice × model-slice] of every
    # block leaf and the Block psums its row-parallel projections.
    tp = mesh.shape.get(mesh_lib.MODEL, 1) if mesh is not None else 1
    if tp > 1 and mesh.shape.get(mesh_lib.PIPE, 1) == 1:
        raise ValueError(
            "model axis without a pipe axis: use the dense Transformer "
            "with tp_rules (GSPMD TP) instead of the pipelined path"
        )
    block = Block(stage_cfg, None, False, tp_shards=tp)

    x_mb = microbatch(x, n_microbatches)

    n_stages = mesh.shape.get(mesh_lib.PIPE, 1) if mesh is not None else 1
    layers_per_chunk = cfg.num_layers // (n_stages * n_virtual)

    def run_layers(stage_params, x, mask, mb_key, chunk):
        if mb_key is None:
            def layer(x, p):
                return block.apply({"params": p}, x, mask, train=False), None

            y, _ = jax.lax.scan(layer, x, stage_params)
        else:
            if n_stages > 1:
                # inside the shard_map island each device holds a
                # (data, fsdp) slice of the microbatch and flax draws
                # masks at the LOCAL shape — without this fold every
                # shard would reuse the same mask for different rows
                # (correlated dropout across the batch)
                shard = (jax.lax.axis_index(mesh_lib.DATA)
                         * mesh.shape.get(mesh_lib.FSDP, 1)
                         + jax.lax.axis_index(mesh_lib.FSDP))
                mb_key = jax.random.fold_in(mb_key, shard)

            def layer(x, pl):
                p, li = pl
                lkey = jax.random.fold_in(
                    mb_key, chunk * layers_per_chunk + li)
                return block.apply(
                    {"params": p}, x, mask, train=True,
                    rngs={"dropout": lkey},
                ), None

            y, _ = jax.lax.scan(
                layer, x, (stage_params, jnp.arange(layers_per_chunk)))
        return y

    mask_mb = (
        microbatch(attention_mask.astype(bool), n_microbatches)
        if attention_mask is not None else None
    )
    # positional adapters: pipeline_apply appends (mb_key, chunk) only
    # when rng is given, and aux only when mask_mb is given
    if use_dropout:
        if mask_mb is not None:
            stage_fn = run_layers
        else:
            stage_fn = lambda p, x, k, c: run_layers(p, x, None, k, c)
    else:
        if mask_mb is not None:
            stage_fn = lambda p, x, a: run_layers(p, x, a, None, None)
        else:
            stage_fn = lambda p, x: run_layers(p, x, None, None, None)
    y = pipeline_apply(
        stage_fn, pparams["blocks"], x_mb, mesh, aux_mb=mask_mb,
        n_virtual=n_virtual,
        param_specs=(
            pipeline_param_specs(pparams, tp=True)["blocks"]
            if tp > 1 else None
        ),
        rng=rng if use_dropout else None,
    )
    y = unmicrobatch(y)

    if cfg.pre_ln:
        y = nn.LayerNorm(dtype=jnp.float32).apply(
            {"params": ends["final_ln"]}, y
        ).astype(dtype)
    if positions is not None:
        if cfg.causal:
            raise ValueError(
                "positions gather is the MLM head path; causal LMs "
                "predict every position"
            )
        # MLM gathered head (see Transformer.__call__): head + vocab
        # projection on [B,K,d]; runs outside the pipeline island, so the
        # pipelined path gets the same memory/FLOPs win
        y = jnp.take_along_axis(
            y, positions[..., None].astype(jnp.int32), axis=1
        )
    if not cfg.causal:
        y = nn.Dense(cfg.d_model, dtype=dtype).apply(
            {"params": ends["mlm_transform"]}, y
        )
        y = nn.gelu(y)
        y = nn.LayerNorm(dtype=jnp.float32).apply(
            {"params": ends["mlm_ln"]}, y
        ).astype(dtype)
    logits = _head_projection(y, embed_tbl, cfg.head_dtype)
    return logits + ends["mlm_bias"]


def make_pipelined_init_fn(cfg: TransformerConfig, n_stages: int,
                           seq_len: int, n_virtual: int = 1):
    """init_fn(rng) -> (pipeline-layout params, {}): init the dense family,
    transpose into the pipe layout."""
    _check_pipelineable(cfg, n_stages, n_virtual)
    base = make_init_fn(
        Transformer(dataclasses.replace(cfg, seq_impl=None)), seq_len
    )

    def init_fn(rng):
        params, _ = base(rng)
        return to_pipeline_params(params, cfg, n_stages, n_virtual), {}

    return init_fn


def pipelined_lm_loss_fn(cfg: TransformerConfig, mesh: Any,
                         n_microbatches: int, n_virtual: int = 1):
    """Engine LossFn: next-token loss through the pipelined forward.
    Dropout active per cfg.dropout — same training semantics as the
    dense lm_loss_fn (per-step engine rng threaded through the tick)."""

    def loss_fn(params, model_state, batch, rng):
        ids = batch["input_ids"]
        logits = pipelined_apply(
            params, ids, batch.get("attention_mask"), cfg, mesh,
            n_microbatches, n_virtual, train=True, rng=rng,
        )
        labels = _shifted_lm_labels(ids, batch.get("attention_mask"))
        loss, acc = _masked_xent(logits, labels)
        return loss, (model_state, {"accuracy": acc})

    return loss_fn


def pipelined_mlm_loss_fn(cfg: TransformerConfig, mesh: Any,
                          n_microbatches: int, n_virtual: int = 1):
    """Engine LossFn: masked-LM loss through the pipelined forward.
    Dropout active per cfg.dropout (see pipelined_lm_loss_fn)."""

    def loss_fn(params, model_state, batch, rng):
        positions, labels = _mlm_targets(batch)
        logits = pipelined_apply(
            params, batch["input_ids"], batch.get("attention_mask"), cfg,
            mesh, n_microbatches, n_virtual, train=True, rng=rng,
            positions=positions,
        )
        loss, acc = _masked_xent(logits, labels)
        return loss, (model_state, {"accuracy": acc})

    return loss_fn


# ---------------------------------------------------------------------------
# Loss adapters (train-engine LossFn contract, cf. models/common.py)
# ---------------------------------------------------------------------------



def _xent_eval_stats(logits, labels):
    """SUMMED per-token eval statistics over valid (non-IGNORE) positions
    — summed, not averaged, so sharded eval batches aggregate exactly
    (models/common.classification_eval_fn contract; the runner derives
    loss/accuracy ratios)."""
    valid = labels != IGNORE_INDEX
    safe = jnp.where(valid, labels, 0)
    xent = -jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    per_tok = jnp.take_along_axis(xent, safe[..., None], axis=-1)[..., 0]
    return {
        "loss_sum": jnp.where(valid, per_tok, 0.0).sum(),
        "correct": jnp.where(
            valid, jnp.argmax(logits, -1) == safe, False
        ).sum().astype(jnp.float32),
        "count": valid.sum().astype(jnp.float32),
    }


def _shifted_lm_labels(ids, attention_mask=None):
    """Next-token labels: position t predicts ids[t+1]; the final
    position (and positions whose TARGET is padding) are IGNOREd. Shared
    by lm_loss_fn and lm_eval_fn."""
    labels = jnp.concatenate(
        [ids[:, 1:], jnp.full_like(ids[:, :1], IGNORE_INDEX)], axis=1
    )
    if attention_mask is not None:
        label_valid = jnp.concatenate(
            [attention_mask[:, 1:] > 0,
             jnp.zeros_like(attention_mask[:, :1], bool)], axis=1
        )
        labels = jnp.where(label_valid, labels, IGNORE_INDEX)
    return labels


def _mlm_targets(batch):
    """(positions, labels) for the MLM head: the gathered-head batch
    format {"masked_positions" [B,K], "masked_labels" [B,K]} when the
    pipeline provides it (TextDataConfig.max_predictions > 0 — the
    reference's masked_lm_positions format), else the dense [B,S]
    labels with IGNORE_INDEX on unmasked positions."""
    if "masked_positions" in batch:
        return batch["masked_positions"], batch["masked_labels"]
    return None, batch["labels"]


def transformer_eval_fn(model: Transformer, *, mlm: bool):
    """Summed-stats eval, MLM or next-token (reference analog: the eval
    loop over latest_checkpoint, SURVEY.md §3.5). Same ``mlm`` switch as
    :func:`pipelined_eval_fn`."""

    def eval_fn(params, model_state, batch):
        ids = batch["input_ids"]
        positions, labels = (
            _mlm_targets(batch) if mlm
            else (None, _shifted_lm_labels(ids, batch.get("attention_mask")))
        )
        logits, _ = model.apply(
            {"params": params}, ids, batch.get("attention_mask"),
            train=False, mutable=["losses"], positions=positions,
        )
        return _xent_eval_stats(logits, labels)

    return eval_fn


def mlm_eval_fn(model: Transformer):
    return transformer_eval_fn(model, mlm=True)


def lm_eval_fn(model: Transformer, xent_chunk: int = 0):
    """``xent_chunk > 0``: summed stats computed per sequence chunk from
    hidden states (same chunking as :func:`chunked_lm_loss_fn`) — a
    large-vocab training run must not OOM at the final eval it was
    configured to avoid OOMing in."""
    if xent_chunk <= 0:
        return transformer_eval_fn(model, mlm=False)

    def eval_fn(params, model_state, batch):
        ids = batch["input_ids"]
        labels = _shifted_lm_labels(ids, batch.get("attention_mask"))
        h, _ = model.apply(
            {"params": params}, ids, batch.get("attention_mask"),
            train=False, mutable=["losses"], return_hidden=True,
        )
        return _chunked_xent_stats(h, labels, params, xent_chunk,
                                   model.cfg.head_dtype)

    return eval_fn


def _head_projection(x, embedding, head_dtype: str):
    """The tied-embedding vocab projection, f32 logits out — ONE
    definition shared by the model head and the chunked loss/eval so
    the two cannot drift. head_dtype="float32" reproduces
    ``Embed.attend`` exactly (f32 dot); "bfloat16" runs the matmul on
    the fast MXU tier with f32 accumulation."""
    hd = jnp.dtype(head_dtype)
    return jnp.dot(x.astype(hd), embedding.astype(hd).T,
                   preferred_element_type=jnp.float32)


def _chunked_xent_stats(h, labels, params, chunk_size: int,
                        head_dtype: str = "float32"):
    """Summed xent stats from hidden states, vocab head applied per
    sequence chunk (shared by chunked_lm_loss_fn and the chunked eval;
    projection via the same :func:`_head_projection` as the model head)."""
    emb = params["tok_embed"]["embedding"]
    bias = params["mlm_bias"]
    B, S, d = h.shape
    C = min(chunk_size, S)
    if S % C:
        raise ValueError(
            f"seq len {S} not divisible by xent chunk size {C} — set "
            f"model.xent_chunk (BENCH_XENT_CHUNK in the bench) to a "
            f"divisor of the sequence length, or 0 for the dense loss")
    N = S // C
    hs = h.reshape(B, N, C, d).swapaxes(0, 1)      # [N, B, C, d]
    ls = labels.reshape(B, N, C).swapaxes(0, 1)    # [N, B, C]

    @jax.checkpoint
    def body(carry, inp):
        hc, lc = inp
        logits = _head_projection(hc, emb, head_dtype) + bias
        s = _xent_eval_stats(logits, lc)
        return (carry[0] + s["loss_sum"], carry[1] + s["correct"],
                carry[2] + s["count"]), None

    zero = jnp.zeros((), jnp.float32)
    (loss_sum, correct, count), _ = jax.lax.scan(
        body, (zero, zero, zero), (hs, ls))
    return {"loss_sum": loss_sum, "correct": correct, "count": count}


def causal_lm_loss(model: Transformer, xent_chunk: int = 0):
    """THE causal-LM loss selector (one home for the chunk>0 ladder so
    the workload builder and the bench cannot drift): chunked when
    ``xent_chunk > 0``, dense otherwise."""
    return (chunked_lm_loss_fn(model, xent_chunk) if xent_chunk > 0
            else lm_loss_fn(model))


def pipelined_eval_fn(cfg: TransformerConfig, mesh: Any,
                      n_microbatches: int, n_virtual: int = 1,
                      *, mlm: bool):
    """Summed-stats eval through the pipelined forward (pipe-layout
    params), MLM or next-token."""

    def eval_fn(params, model_state, batch):
        ids = batch["input_ids"]
        positions, labels = (
            _mlm_targets(batch) if mlm
            else (None, _shifted_lm_labels(ids, batch.get("attention_mask")))
        )
        logits = pipelined_apply(
            params, ids, batch.get("attention_mask"), cfg, mesh,
            n_microbatches, n_virtual, positions=positions,
        )
        return _xent_eval_stats(logits, labels)

    return eval_fn


def _masked_xent(logits, labels):
    """Mean cross-entropy over positions where labels != IGNORE_INDEX —
    the ratio form of _xent_eval_stats (one implementation of the masked
    gather/argmax math)."""
    s = _xent_eval_stats(logits, labels)
    count = jnp.maximum(s["count"], 1)
    return s["loss_sum"] / count, s["correct"] / count


def mlm_loss_fn(model: Transformer):
    """Masked-LM loss. Batch: {"input_ids" [B,S], "labels" [B,S] with
    IGNORE_INDEX on unmasked positions, optional "attention_mask" [B,S]}."""

    def loss_fn(params, model_state, batch, rng):
        positions, labels = _mlm_targets(batch)
        logits, mut = model.apply(
            {"params": params}, batch["input_ids"],
            batch.get("attention_mask"), train=True, rngs={"dropout": rng},
            mutable=["losses"], positions=positions,
        )
        loss, acc = _masked_xent(logits, labels)
        loss = loss + collect_aux_loss(mut)  # MoE router load-balance
        return loss, (model_state, {"accuracy": acc})

    return loss_fn


def lm_loss_fn(model: Transformer):
    """Next-token loss for causal models. Batch: {"input_ids" [B,S]};
    position t predicts token t+1."""

    def loss_fn(params, model_state, batch, rng):
        ids = batch["input_ids"]
        logits, mut = model.apply(
            {"params": params}, ids, batch.get("attention_mask"),
            train=True, rngs={"dropout": rng}, mutable=["losses"],
        )
        labels = _shifted_lm_labels(ids, batch.get("attention_mask"))
        loss, acc = _masked_xent(logits, labels)
        loss = loss + collect_aux_loss(mut)  # MoE router load-balance
        return loss, (model_state, {"accuracy": acc})

    return loss_fn


def chunked_lm_loss_fn(model: Transformer, chunk_size: int):
    """Next-token loss that never materializes the full ``[B, S, vocab]``
    logits tensor — the memory bomb of large-vocab causal LMs (GPT-2
    vocab 50304 at B=128, S=512 is 13 GB in f32 before the backward,
    over a v5e's entire HBM; cf. the gathered MLM head, which solves the
    same problem for BERT by gathering K positions — a causal LM predicts
    EVERY position, so the fix is chunking instead of gathering).

    The block stack runs once (``return_hidden=True``); the tied-embedding
    projection + masked cross-entropy then run per sequence chunk inside a
    rematerialized ``lax.scan``: peak logits memory drops from
    ``[B, S, V]`` to ``[B, chunk, V]`` (the backward recomputes each
    chunk's logits from the saved ``[B, chunk, d]`` hiddens).
    Numerically identical to :func:`lm_loss_fn` — same f32 projection
    math as the model head, exact-parity-tested."""

    def loss_fn(params, model_state, batch, rng):
        ids = batch["input_ids"]
        h, mut = model.apply(
            {"params": params}, ids, batch.get("attention_mask"),
            train=True, rngs={"dropout": rng}, mutable=["losses"],
            return_hidden=True,
        )
        labels = _shifted_lm_labels(ids, batch.get("attention_mask"))
        s = _chunked_xent_stats(h, labels, params, chunk_size,
                                model.cfg.head_dtype)
        count = jnp.maximum(s["count"], 1)
        loss = s["loss_sum"] / count + collect_aux_loss(mut)
        return loss, (model_state, {"accuracy": s["correct"] / count})

    return loss_fn


def make_init_fn(model: Transformer, seq_len: int):
    """init_fn(rng) -> (params, {}) for init_train_state.

    Initializes through a dense twin (seq_impl=None, no mesh): attention
    has no impl-dependent parameters, and the twin avoids tracing shard_map
    islands with a batch-1 dummy that a data axis couldn't divide."""
    cfg = model.cfg
    init_model = (
        Transformer(dataclasses.replace(cfg, seq_impl=None))
        if (model.mesh is not None or cfg.seq_impl is not None)
        else model
    )

    def init_fn(rng):
        dummy = jnp.zeros((1, seq_len), jnp.int32)
        variables = init_model.init({"params": rng, "dropout": rng}, dummy,
                                    train=False)
        return variables["params"], {}

    return init_fn


def _block_counts(cfg: TransformerConfig) -> tuple[int, int]:
    """(number of dense-FFN blocks, number of MoE blocks)."""
    if cfg.num_experts <= 0:
        return cfg.num_layers, 0
    n_moe = sum(
        1 for i in range(cfg.num_layers)
        if i % cfg.moe_every == cfg.moe_every - 1
    )
    return cfg.num_layers - n_moe, n_moe


def _ffn_params(cfg: TransformerConfig, experts: int) -> int:
    """FFN params per block with ``experts`` expert copies (1 = dense)."""
    d, f = cfg.d_model, cfg.d_ff
    ffn = experts * (2 * d * f + f + d)
    if experts > 1:
        ffn += d * cfg.num_experts + cfg.num_experts  # router
    return ffn


def param_count(cfg: TransformerConfig) -> int:
    """Analytic parameter count (embeddings + blocks + heads + experts)."""
    d, L = cfg.d_model, cfg.num_layers
    embed = cfg.vocab_size * d + cfg.max_len * d
    embed += 2 * d  # embed_ln (post-LN) or final_ln (pre-LN)
    attn = 4 * d * d + 4 * d  # qkv+out kernels + biases
    ln = 4 * d  # 2 LayerNorms
    head = 0 if cfg.causal else d * d + 3 * d
    n_dense, n_moe = _block_counts(cfg)
    blocks = (
        L * (attn + ln)
        + n_dense * _ffn_params(cfg, 1)
        + n_moe * _ffn_params(cfg, cfg.num_experts)
    )
    return embed + blocks + head + cfg.vocab_size


def active_param_count(cfg: TransformerConfig) -> int:
    """Params touched per token: MoE blocks engage only top_k experts —
    this is the N that enters the 2N FLOPs/token estimate."""
    if cfg.num_experts <= 0:
        return param_count(cfg)
    _, n_moe = _block_counts(cfg)
    d, f = cfg.d_model, cfg.d_ff
    idle_experts = cfg.num_experts - cfg.moe_top_k
    return param_count(cfg) - n_moe * idle_experts * (2 * d * f + f + d)


def flops_per_example(cfg: TransformerConfig, seq_len: int,
                      n_predictions: int | None = None) -> float:
    """Forward FLOPs per example at ``seq_len`` (×3 for training in the
    engine's MFU accounting, utils/flops.py train_flops_multiplier).
    Uses *active* params so MoE MFU accounting stays honest (SURVEY.md §7
    'MFU accounting honesty').

    ``n_predictions``: gathered MLM head (Transformer positions arg) —
    the head (mlm_transform d×d + tied d×vocab projection) runs on K
    positions instead of all seq_len; subtract the skipped positions'
    share so declared FLOPs stay honest (tests/test_flops_contract.py).
    """
    base = seq_len * flops_lib.transformer_flops_per_token(
        active_param_count(cfg), seq_len, cfg.num_layers, cfg.d_model
    )
    if n_predictions is not None and not cfg.causal:
        per_pos_head = 2.0 * (cfg.vocab_size * cfg.d_model
                              + cfg.d_model * cfg.d_model)
        base -= (seq_len - n_predictions) * per_pos_head
    return base
