"""ResNet-50 — the BASELINE primary-metric workload (BASELINE.json:2,9).

Reference analog: the harness's ResNet-50 train script over PS/worker
(SURVEY.md §2a). TPU-first choices: bf16 conv/matmul compute with f32
params and f32 BatchNorm statistics (MXU-friendly, numerically safe), NHWC
layout (TPU conv native), and BatchNorm that becomes cross-replica synced
for free under GSPMD (the batch mean reduces over the sharded batch axis).
v1.5 variant (stride-2 on the 3x3, not the 1x1 — the MLPerf standard).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import flax.linen as nn
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: tuple = (3, 4, 6, 3)  # ResNet-50
    num_classes: int = 1000
    width: int = 64
    dtype: str = "bfloat16"
    # BatchNorm *output* dtype; None = follow `dtype`. Statistics are always
    # computed in f32 (flax normalization upcasts internally); bf16 output
    # halves the HBM traffic of the normalize/scale pass — the activations
    # between BN and the next conv are the widest tensors in the net
    # (round-1 used f32 BN output: -25% throughput, PERF_NOTES.md).
    norm_dtype: str | None = None
    bn_momentum: float = 0.9
    bn_epsilon: float = 1e-5
    # "conv": standard 7x7/2 stem. "space_to_depth": fold the image 2x2
    # (H,W,3)→(H/2,W/2,12) and run a 4x4/1 conv — same receptive field as
    # an 8x8/2 conv (7x7 kernel zero-padded), but 12 input channels pack
    # the MXU's contracting dimension 4x better than 3 (the MLPerf TPU
    # ResNet conv0 optimization).
    stem: str = "conv"


def space_to_depth(x, block: int):
    """(B, H, W, C) → (B, H/b, W/b, C·b²): fold b×b spatial patches into
    channels. Pure reshape/transpose — XLA fuses it into the consumer."""
    b_, h, w, c = x.shape
    x = x.reshape(b_, h // block, block, w // block, block, c)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(
        b_, h // block, w // block, c * block * block
    )


class BottleneckBlock(nn.Module):
    filters: int
    strides: int
    cfg: ResNetConfig

    @nn.compact
    def __call__(self, x, *, train: bool):
        dtype = jnp.dtype(self.cfg.dtype)
        conv = partial(nn.Conv, use_bias=False, dtype=dtype,
                       kernel_init=nn.initializers.he_normal())
        # BN computes statistics in f32 regardless of output dtype.
        bn = partial(nn.BatchNorm, use_running_average=not train,
                     momentum=self.cfg.bn_momentum, epsilon=self.cfg.bn_epsilon,
                     dtype=jnp.dtype(self.cfg.norm_dtype or self.cfg.dtype))
        residual = x
        y = conv(self.filters, (1, 1), name="conv1")(x)
        y = bn(name="bn1")(y)
        y = nn.relu(y)
        y = conv(self.filters, (3, 3), strides=(self.strides, self.strides),
                 name="conv2")(y)  # v1.5: stride on the 3x3
        y = bn(name="bn2")(y)
        y = nn.relu(y)
        y = conv(self.filters * 4, (1, 1), name="conv3")(y)
        # zero-init last BN scale: residual branch starts as identity
        y = bn(name="bn3", scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters * 4, (1, 1),
                            strides=(self.strides, self.strides),
                            name="proj_conv")(residual)
            residual = bn(name="proj_bn")(residual)
        return nn.relu(residual.astype(y.dtype) + y)


class ResNet(nn.Module):
    cfg: ResNetConfig

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        cfg = self.cfg
        dtype = jnp.dtype(cfg.dtype)
        x = x.astype(dtype)
        if cfg.stem == "space_to_depth":
            x = space_to_depth(x, 2)
            x = nn.Conv(cfg.width, (4, 4), strides=(1, 1), use_bias=False,
                        dtype=dtype, kernel_init=nn.initializers.he_normal(),
                        name="stem_conv_s2d")(x)
        elif cfg.stem == "conv":
            x = nn.Conv(cfg.width, (7, 7), strides=(2, 2), use_bias=False,
                        dtype=dtype, kernel_init=nn.initializers.he_normal(),
                        name="stem_conv")(x)
        else:
            raise ValueError(f"Unknown stem {cfg.stem!r}")
        x = nn.BatchNorm(use_running_average=not train, momentum=cfg.bn_momentum,
                         epsilon=cfg.bn_epsilon,
                         dtype=jnp.dtype(cfg.norm_dtype or cfg.dtype),
                         name="stem_bn")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for stage, blocks in enumerate(cfg.stage_sizes):
            for block in range(blocks):
                strides = 2 if stage > 0 and block == 0 else 1
                x = BottleneckBlock(
                    cfg.width * 2**stage, strides, cfg,
                    name=f"stage{stage}_block{block}",
                )(x, train=train)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        # head in f32: the last matmul is tiny; keep logits stable
        return nn.Dense(cfg.num_classes, dtype=jnp.float32, name="head")(x)


def ResNet50(cfg: ResNetConfig | None = None) -> ResNet:
    return ResNet(cfg or ResNetConfig())


def flops_per_example(cfg: ResNetConfig, image_size: int = 224) -> float:
    """Analytic FORWARD FLOPs per image (the §6 honesty rule: model
    arithmetic, not profiler counts). Counts conv/dense MACs ×2. The
    framework-wide contract (utils/flops.py): flops_per_example is always
    forward-only; training consumers apply train_flops_multiplier() in
    exactly one place (MetricsLogger / bench)."""
    total = 0.0
    size = image_size // 2  # stem stride 2 (or s2d fold)
    if cfg.stem == "space_to_depth":
        stem_macs = 12 * 16
    elif cfg.stem == "conv":
        stem_macs = 3 * 49
    else:
        raise ValueError(f"Unknown stem {cfg.stem!r}")
    total += 2.0 * size * size * cfg.width * stem_macs
    size //= 2  # maxpool
    in_c = cfg.width
    for stage, blocks in enumerate(cfg.stage_sizes):
        filters = cfg.width * 2**stage
        for block in range(blocks):
            stride = 2 if stage > 0 and block == 0 else 1
            out_size = size // stride
            # 1x1 in (at input res), 3x3 (strided), 1x1 out
            total += 2.0 * size * size * filters * in_c
            total += 2.0 * out_size * out_size * filters * filters * 9
            total += 2.0 * out_size * out_size * (filters * 4) * filters
            if in_c != filters * 4 or stride != 1:
                total += 2.0 * out_size * out_size * (filters * 4) * in_c
            in_c = filters * 4
            size = out_size
    total += 2.0 * in_c * cfg.num_classes
    return total
