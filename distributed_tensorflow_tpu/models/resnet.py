"""ResNet-50 — the BASELINE primary-metric workload (BASELINE.json:2,9).

Reference analog: the harness's ResNet-50 train script over PS/worker
(SURVEY.md §2a). TPU-first choices: bf16 conv/matmul compute with f32
params and f32 BatchNorm statistics (MXU-friendly, numerically safe), NHWC
layout (TPU conv native), and BatchNorm that becomes cross-replica synced
for free under GSPMD (the batch mean reduces over the sharded batch axis).
v1.5 variant (stride-2 on the 3x3, not the 1x1 — the MLPerf standard).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..parallel import sharding
from ..utils.compat import shard_map

#: Coverage fixture: the stage_sizes=(1, 1) tree (every param family the
#: full ResNet-50 tree repeats — stem, bottleneck convs/BNs incl. the
#: projection shortcut, head). Pinned to the live model by
#: tests/test_sharding.py::test_resnet_coverage_fixture_is_live.
#: (fully literal — the dtflint shard-rules-coverage rule reads it
#: statically)
_RESNET_COVERAGE = (
    "head/bias", "head/kernel",
    "stage0_block0/bn1/bias", "stage0_block0/bn1/scale",
    "stage0_block0/bn2/bias", "stage0_block0/bn2/scale",
    "stage0_block0/bn3/bias", "stage0_block0/bn3/scale",
    "stage0_block0/conv1/kernel", "stage0_block0/conv2/kernel",
    "stage0_block0/conv3/kernel",
    "stage0_block0/proj_bn/bias", "stage0_block0/proj_bn/scale",
    "stage0_block0/proj_conv/kernel",
    "stage1_block0/bn1/bias", "stage1_block0/bn1/scale",
    "stage1_block0/bn2/bias", "stage1_block0/bn2/scale",
    "stage1_block0/bn3/bias", "stage1_block0/bn3/scale",
    "stage1_block0/conv1/kernel", "stage1_block0/conv2/kernel",
    "stage1_block0/conv3/kernel",
    "stage1_block0/proj_bn/bias", "stage1_block0/proj_bn/scale",
    "stage1_block0/proj_conv/kernel",
    "stem_bn/bias", "stem_bn/scale", "stem_conv/kernel",
)

#: Partition-rules table: ResNet trains pure data-parallel — every param
#: is DECLARED replicated (batch sharding rides (data, fsdp) via
#: batch_spec; BatchNorm syncs for free under GSPMD). A one-row table is
#: still the seam: adding a sharded param family later means adding a
#: row here, not hand-authoring a spec tree.
RESNET_RULES = sharding.partition_rules(
    "resnet",
    ((sharding.CATCH_ALL, sharding.REPLICATED),),
    coverage=_RESNET_COVERAGE,
)


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: tuple = (3, 4, 6, 3)  # ResNet-50
    num_classes: int = 1000
    width: int = 64
    dtype: str = "bfloat16"
    # BatchNorm *output* dtype; None = follow `dtype`. Statistics are always
    # computed in f32 (flax normalization upcasts internally); bf16 output
    # halves the HBM traffic of the normalize/scale pass — the activations
    # between BN and the next conv are the widest tensors in the net
    # (round-1 used f32 BN output: -25% throughput, PERF_NOTES.md).
    norm_dtype: str | None = None
    bn_momentum: float = 0.9
    bn_epsilon: float = 1e-5
    # "conv": standard 7x7/2 stem. "space_to_depth": fold the image 2x2
    # (H,W,3)→(H/2,W/2,12) and run a 4x4/1 conv — same receptive field as
    # an 8x8/2 conv (7x7 kernel zero-padded), but 12 input channels pack
    # the MXU's contracting dimension 4x better than 3 (the MLPerf TPU
    # ResNet conv0 optimization).
    stem: str = "conv"
    # "standard": flax Conv/BatchNorm bottlenecks. "fused": Pallas
    # conv1x1+BN kernels (ops/fused_conv_bn.py) — the 1x1 convs absorb the
    # adjacent BN normalize/stats passes (prologue/epilogue), cutting the
    # HBM traffic that bounds the step (PERF_NOTES.md roofline). Same
    # param/batch_stats tree as "standard" (checkpoints interoperate).
    block_impl: str = "standard"


def space_to_depth(x, block: int):
    """(B, H, W, C) → (B, H/b, W/b, C·b²): fold b×b spatial patches into
    channels. Pure reshape/transpose — XLA fuses it into the consumer."""
    b_, h, w, c = x.shape
    x = x.reshape(b_, h // block, block, w // block, block, c)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(
        b_, h // block, w // block, c * block * block
    )


class BottleneckBlock(nn.Module):
    filters: int
    strides: int
    cfg: ResNetConfig

    @nn.compact
    def __call__(self, x, *, train: bool):
        dtype = jnp.dtype(self.cfg.dtype)
        conv = partial(nn.Conv, use_bias=False, dtype=dtype,
                       kernel_init=nn.initializers.he_normal())
        # BN computes statistics in f32 regardless of output dtype.
        bn = partial(nn.BatchNorm, use_running_average=not train,
                     momentum=self.cfg.bn_momentum, epsilon=self.cfg.bn_epsilon,
                     dtype=jnp.dtype(self.cfg.norm_dtype or self.cfg.dtype))
        residual = x
        y = conv(self.filters, (1, 1), name="conv1")(x)
        y = bn(name="bn1")(y)
        y = nn.relu(y)
        y = conv(self.filters, (3, 3), strides=(self.strides, self.strides),
                 name="conv2")(y)  # v1.5: stride on the 3x3
        y = bn(name="bn2")(y)
        y = nn.relu(y)
        y = conv(self.filters * 4, (1, 1), name="conv3")(y)
        # zero-init last BN scale: residual branch starts as identity
        y = bn(name="bn3", scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters * 4, (1, 1),
                            strides=(self.strides, self.strides),
                            name="proj_conv")(residual)
            residual = bn(name="proj_bn")(residual)
        return nn.relu(residual.astype(y.dtype) + y)


# ---------------------------------------------------------------------------
# Fused-kernel bottleneck (ops/fused_conv_bn.py): same params, same math,
# 1x1 convs absorb the adjacent BN passes
# ---------------------------------------------------------------------------


class _ConvKernel(nn.Module):
    """Parameter-only scope so the fused block's tree matches the standard
    block's (``conv1/kernel`` etc. — checkpoints interoperate)."""

    shape: tuple

    @nn.compact
    def __call__(self):
        return self.param("kernel", nn.initializers.he_normal(), self.shape)


class _BNState(nn.Module):
    """scale/bias params + batch_stats mean/var, flax BatchNorm naming."""

    features: int
    zero_scale: bool = False

    @nn.compact
    def __call__(self):
        init_scale = (
            nn.initializers.zeros if self.zero_scale else nn.initializers.ones
        )
        scale = self.param("scale", init_scale, (self.features,))
        bias = self.param("bias", nn.initializers.zeros, (self.features,))
        mean = self.variable(
            "batch_stats", "mean",
            lambda: jnp.zeros((self.features,), jnp.float32),
        )
        var = self.variable(
            "batch_stats", "var",
            lambda: jnp.ones((self.features,), jnp.float32),
        )
        return scale, bias, mean, var


class FusedBottleneckBlock(nn.Module):
    """BottleneckBlock with the 1x1 convs running through the fused
    Pallas conv+BN kernels (train mode): conv1/conv3/proj_conv emit their
    output BN's statistics from the kernel epilogue, and conv3 applies
    bn2+ReLU in its prologue — the normalized tensor between bn2 and conv3
    and all three stats read-passes never touch HBM. BatchNorm statistics
    reduce over the *global* batch (psum over data/fsdp inside a shard_map
    island when a mesh is given) — the same sync-BN-under-GSPMD semantics
    as the standard block. Eval uses plain XLA ops with running stats."""

    filters: int
    strides: int
    cfg: ResNetConfig
    mesh: Any = None

    @nn.compact
    def __call__(self, x, *, train: bool):
        from ..ops.fused_conv_bn import (
            bn_scale_shift, conv1x1_bn_act, moments_from_sums,
        )
        from ..parallel import mesh as mesh_lib

        cfg = self.cfg
        f, s = self.filters, self.strides
        cin = x.shape[-1]
        dtype = jnp.dtype(cfg.dtype)
        out_dtype = jnp.dtype(cfg.norm_dtype or cfg.dtype)
        eps, mom = cfg.bn_epsilon, cfg.bn_momentum

        w1 = _ConvKernel((1, 1, cin, f), name="conv1")()
        g1, b1, m1, v1 = _BNState(f, name="bn1")()
        w2 = _ConvKernel((3, 3, f, f), name="conv2")()
        g2, b2, m2, v2 = _BNState(f, name="bn2")()
        w3 = _ConvKernel((1, 1, f, 4 * f), name="conv3")()
        g3, b3, m3, v3 = _BNState(4 * f, zero_scale=True, name="bn3")()
        need_proj = cin != 4 * f or s != 1
        if need_proj:
            wp = _ConvKernel((1, 1, cin, 4 * f), name="proj_conv")()
            gp, bp, mp, vp = _BNState(4 * f, name="proj_bn")()

        conv3x3 = lambda h: jax.lax.conv_general_dilated(
            h, w2.astype(dtype), (s, s), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )

        if not train:
            # eval: running stats, plain XLA (perf-uncritical path)
            def aff(y, g, b, m, v):
                sc, sh = bn_scale_shift(m, v, g, b, eps)
                return y.astype(jnp.float32) * sc + sh
            dot1x1 = lambda h, w: jnp.einsum(
                "bhwc,cd->bhwd", h, w.reshape(w.shape[2], w.shape[3]).astype(dtype)
            )
            h1 = nn.relu(aff(dot1x1(x, w1), g1, b1, m1.value, v1.value)).astype(dtype)
            y2 = conv3x3(h1)
            h2 = nn.relu(aff(y2, g2, b2, m2.value, v2.value)).astype(dtype)
            y3 = aff(dot1x1(h2, w3), g3, b3, m3.value, v3.value)
            if need_proj:
                xs = x[:, ::s, ::s, :]
                res = aff(dot1x1(xs, wp), gp, bp, mp.value, vp.value)
            else:
                res = x.astype(jnp.float32)
            return nn.relu(y3 + res).astype(out_dtype)

        axis_names = None
        if self.mesh is not None:
            axis_names = tuple(
                a for a in mesh_lib.BATCH_AXES if a in self.mesh.shape
            )

        def block_fn(x, w1, w2f, w3, wp_, g1, b1, g2, b2, g3, b3, gp_, bp_):
            psum = (
                (lambda t: jax.lax.psum(t, axis_names))
                if axis_names else (lambda t: t)
            )
            B, H, W, _ = x.shape
            x2 = x.reshape(-1, cin)
            w1_2 = w1.reshape(cin, f).astype(dtype)
            y1, s1, q1 = conv1x1_bn_act(
                x2, w1_2, emit_stats=True, out_dtype=out_dtype
            )
            n1 = psum(jnp.float32(y1.shape[0]))
            mu1, var1 = moments_from_sums(psum(s1), psum(q1), n1)
            sc1, sh1 = bn_scale_shift(mu1, var1, g1, b1, eps)
            h1 = nn.relu(y1.astype(jnp.float32) * sc1 + sh1).astype(dtype)
            y2 = jax.lax.conv_general_dilated(
                h1.reshape(B, H, W, f), w2f.astype(dtype), (s, s), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            )
            y2_2 = y2.astype(out_dtype).reshape(-1, f)
            st2 = y2_2.astype(jnp.float32)
            n2 = psum(jnp.float32(y2_2.shape[0]))
            mu2, var2 = moments_from_sums(
                psum(st2.sum(0)), psum((st2 * st2).sum(0)), n2
            )
            sc2, sh2 = bn_scale_shift(mu2, var2, g2, b2, eps)
            y3, s3, q3 = conv1x1_bn_act(
                y2_2, w3.reshape(f, 4 * f).astype(dtype), sc2, sh2,
                relu=True, emit_stats=True, out_dtype=out_dtype,
            )
            mu3, var3 = moments_from_sums(psum(s3), psum(q3), n2)
            sc3, sh3 = bn_scale_shift(mu3, var3, g3, b3, eps)
            out = y3.astype(jnp.float32) * sc3 + sh3
            stats = [mu1, var1, mu2, var2, mu3, var3]
            if need_proj:
                xs = x[:, ::s, ::s, :].reshape(-1, cin)
                yp, sp, qp = conv1x1_bn_act(
                    xs, wp_.reshape(cin, 4 * f).astype(dtype),
                    emit_stats=True, out_dtype=out_dtype,
                )
                mup, varp = moments_from_sums(psum(sp), psum(qp), n2)
                scp, shp = bn_scale_shift(mup, varp, gp_, bp_, eps)
                res = yp.astype(jnp.float32) * scp + shp
                stats += [mup, varp]
            else:
                res = x.reshape(-1, 4 * f).astype(jnp.float32)
            out = nn.relu(out + res).astype(out_dtype)
            # SAME-padded stride-s conv (and the ::s residual slice) emit
            # ceil(H/s), not floor
            Ho, Wo = -(-H // s), -(-W // s)
            return out.reshape(B, Ho, Wo, 4 * f), tuple(stats)

        wp_in = wp if need_proj else jnp.zeros((1, 1, cin, 4 * f), w1.dtype)
        gp_in = gp if need_proj else jnp.zeros((4 * f,), g1.dtype)
        bp_in = bp if need_proj else jnp.zeros((4 * f,), b1.dtype)
        args = (x, w1, w2, w3, wp_in, g1, b1, g2, b2, g3, b3, gp_in, bp_in)
        if axis_names:
            bspec = P(axis_names, None, None, None)
            fn = shard_map(
                block_fn,
                mesh=self.mesh,
                in_specs=(bspec,) + (P(),) * 12,
                out_specs=(bspec, tuple(P() for _ in range(8 if need_proj else 6))),
                check_vma=False,
            )
            out, stats = fn(*args)
        else:
            out, stats = block_fn(*args)

        if not self.is_initializing():
            upd = lambda var, new: setattr(
                var, "value", mom * var.value + (1.0 - mom) * new
            )
            upd(m1, stats[0]); upd(v1, stats[1])
            upd(m2, stats[2]); upd(v2, stats[3])
            upd(m3, stats[4]); upd(v3, stats[5])
            if need_proj:
                upd(mp, stats[6]); upd(vp, stats[7])
        return out


class ResNet(nn.Module):
    cfg: ResNetConfig
    mesh: Any = None

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        cfg = self.cfg
        dtype = jnp.dtype(cfg.dtype)
        x = x.astype(dtype)
        if cfg.stem == "space_to_depth":
            x = space_to_depth(x, 2)
            x = nn.Conv(cfg.width, (4, 4), strides=(1, 1), use_bias=False,
                        dtype=dtype, kernel_init=nn.initializers.he_normal(),
                        name="stem_conv_s2d")(x)
        elif cfg.stem == "conv":
            x = nn.Conv(cfg.width, (7, 7), strides=(2, 2), use_bias=False,
                        dtype=dtype, kernel_init=nn.initializers.he_normal(),
                        name="stem_conv")(x)
        else:
            raise ValueError(f"Unknown stem {cfg.stem!r}")
        x = nn.BatchNorm(use_running_average=not train, momentum=cfg.bn_momentum,
                         epsilon=cfg.bn_epsilon,
                         dtype=jnp.dtype(cfg.norm_dtype or cfg.dtype),
                         name="stem_bn")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for stage, blocks in enumerate(cfg.stage_sizes):
            for block in range(blocks):
                strides = 2 if stage > 0 and block == 0 else 1
                name = f"stage{stage}_block{block}"
                if cfg.block_impl == "fused":
                    x = FusedBottleneckBlock(
                        cfg.width * 2**stage, strides, cfg, self.mesh,
                        name=name,
                    )(x, train=train)
                elif cfg.block_impl == "standard":
                    x = BottleneckBlock(
                        cfg.width * 2**stage, strides, cfg, name=name,
                    )(x, train=train)
                else:
                    raise ValueError(f"Unknown block_impl {cfg.block_impl!r}")
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        # head in f32: the last matmul is tiny; keep logits stable
        return nn.Dense(cfg.num_classes, dtype=jnp.float32, name="head")(x)


def ResNet50(cfg: ResNetConfig | None = None, mesh: Any = None) -> ResNet:
    return ResNet(cfg or ResNetConfig(), mesh)


def flops_per_example(cfg: ResNetConfig, image_size: int = 224) -> float:
    """Analytic FORWARD FLOPs per image (the §6 honesty rule: model
    arithmetic, not profiler counts). Counts conv/dense MACs ×2. The
    framework-wide contract (utils/flops.py): flops_per_example is always
    forward-only; training consumers apply train_flops_multiplier() in
    exactly one place (MetricsLogger / bench)."""
    total = 0.0
    size = image_size // 2  # stem stride 2 (or s2d fold)
    if cfg.stem == "space_to_depth":
        stem_macs = 12 * 16
    elif cfg.stem == "conv":
        stem_macs = 3 * 49
    else:
        raise ValueError(f"Unknown stem {cfg.stem!r}")
    total += 2.0 * size * size * cfg.width * stem_macs
    size //= 2  # maxpool
    in_c = cfg.width
    for stage, blocks in enumerate(cfg.stage_sizes):
        filters = cfg.width * 2**stage
        for block in range(blocks):
            stride = 2 if stage > 0 and block == 0 else 1
            out_size = size // stride
            # 1x1 in (at input res), 3x3 (strided), 1x1 out
            total += 2.0 * size * size * filters * in_c
            total += 2.0 * out_size * out_size * filters * filters * 9
            total += 2.0 * out_size * out_size * (filters * 4) * filters
            if in_c != filters * 4 or stride != 1:
                total += 2.0 * out_size * out_size * (filters * 4) * in_c
            in_c = filters * 4
            size = out_size
    total += 2.0 * in_c * cfg.num_classes
    return total
