"""Pipeline-parallel decoder LM: embed/head pipe-replicated, transformer
blocks sharded stage-wise over the ``pipe`` mesh axis.

The full-integration demonstration of parallel/pipeline.py (SURVEY.md §2c
'Pipeline parallel' row): parameters are a plain pytree (the train engine's
LossFn contract is framework-agnostic — flax is a convenience, not a
requirement), with every block leaf carrying a leading ``[n_stages,
layers_per_stage, ...]`` dim; stage s scans its own layer slice. The
heterogeneous ends (token embedding lookup, final LN + tied head) run
outside the shard_map island, replicated over ``pipe`` — the standard
shape-preservation constraint of SPMD pipelining (pipeline.py docstring).

Composes pp×dp/fsdp: the batch dim stays sharded over (data, fsdp) inside
the pipeline's shard_map. Deterministic (no dropout) — pipelined
pretraining at this scale regularizes with data, not dropout.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.attention import blockwise_attention
from ..parallel import mesh as mesh_lib
from ..parallel.pipeline import (
    microbatch,
    pipeline_apply,
    stage_param_specs,
    unmicrobatch,
)
from .transformer import IGNORE_INDEX, _masked_xent


@dataclasses.dataclass(frozen=True)
class PipelinedLMConfig:
    vocab_size: int = 50304
    max_len: int = 1024
    num_layers: int = 12
    d_model: int = 768
    num_heads: int = 12
    d_ff: int = 3072
    n_stages: int = 2
    n_microbatches: int = 4
    dtype: str = "bfloat16"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.num_heads

    @property
    def layers_per_stage(self) -> int:
        if self.num_layers % self.n_stages:
            raise ValueError(
                f"num_layers={self.num_layers} not divisible by "
                f"n_stages={self.n_stages}"
            )
        return self.num_layers // self.n_stages


def _init_block(key, cfg: PipelinedLMConfig):
    d, f = cfg.d_model, cfg.d_ff
    ks = jax.random.split(key, 6)
    norm = lambda k, shape, scale: jax.random.normal(k, shape, jnp.float32) * scale
    return {
        "ln1_scale": jnp.ones((d,)), "ln1_bias": jnp.zeros((d,)),
        "wqkv": norm(ks[0], (d, 3 * d), 0.02), "bqkv": jnp.zeros((3 * d,)),
        "wo": norm(ks[1], (d, d), 0.02), "bo": jnp.zeros((d,)),
        "ln2_scale": jnp.ones((d,)), "ln2_bias": jnp.zeros((d,)),
        "w_in": norm(ks[2], (d, f), 0.02), "b_in": jnp.zeros((f,)),
        "w_out": norm(ks[3], (f, d), 0.02), "b_out": jnp.zeros((d,)),
    }


def init_params(key, cfg: PipelinedLMConfig):
    kb, ke, kp = jax.random.split(key, 3)
    S, Lps = cfg.n_stages, cfg.layers_per_stage
    block_keys = jax.random.split(kb, S * Lps).reshape(S, Lps, 2)
    # vmap over (stage, layer) -> every block leaf is [S, Lps, ...]
    blocks = jax.vmap(jax.vmap(lambda k: _init_block(k, cfg)))(block_keys)
    return {
        "embed": jax.random.normal(ke, (cfg.vocab_size, cfg.d_model)) * 0.02,
        "pos": jax.random.normal(kp, (cfg.max_len, cfg.d_model)) * 0.02,
        "final_ln_scale": jnp.ones((cfg.d_model,)),
        "final_ln_bias": jnp.zeros((cfg.d_model,)),
        "head_bias": jnp.zeros((cfg.vocab_size,)),
    } | {"blocks": blocks}


def param_specs(params: Any) -> Any:
    """blocks → P('pipe', ...); everything else pipe-replicated."""
    specs = jax.tree.map(lambda x: P(), params)
    specs["blocks"] = stage_param_specs(params["blocks"])
    return specs


def _ln(x, scale, bias):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    return (x32 - mu) * jax.lax.rsqrt(var + 1e-5) * scale + bias


def _block_apply(p, x, cfg: PipelinedLMConfig):
    """Pre-LN causal block; x [mb, S, d]."""
    dtype = jnp.dtype(cfg.dtype)
    H, D = cfg.num_heads, cfg.head_dim
    mb, S, d = x.shape
    h = _ln(x, p["ln1_scale"], p["ln1_bias"]).astype(dtype)
    qkv = h @ p["wqkv"].astype(dtype) + p["bqkv"].astype(dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    split = lambda t: t.reshape(mb, S, H, D).transpose(0, 2, 1, 3)
    out = blockwise_attention(split(q), split(k), split(v), causal=True)
    out = out.transpose(0, 2, 1, 3).reshape(mb, S, H * D)
    x = x + (out @ p["wo"].astype(dtype) + p["bo"].astype(dtype))
    h = _ln(x, p["ln2_scale"], p["ln2_bias"]).astype(dtype)
    h = jax.nn.gelu(h @ p["w_in"].astype(dtype) + p["b_in"].astype(dtype))
    return x + (h @ p["w_out"].astype(dtype) + p["b_out"].astype(dtype))


def make_stage_fn(cfg: PipelinedLMConfig):
    """(stage_params [Lps, ...], x [mb, S, d]) -> [mb, S, d]: scan the
    stage's layer slice."""

    def stage_fn(stage_params, x):
        def layer(x, p):
            return _block_apply(p, x, cfg), None

        y, _ = jax.lax.scan(layer, x, stage_params)
        return y

    return stage_fn


def apply(params, input_ids, cfg: PipelinedLMConfig, mesh):
    """input_ids [B, S] -> logits [B, S, vocab] (f32, pipe-replicated)."""
    dtype = jnp.dtype(cfg.dtype)
    B, S = input_ids.shape
    x = params["embed"][input_ids] + params["pos"][None, :S]
    x = x.astype(dtype)
    x_mb = microbatch(x, cfg.n_microbatches)
    y = pipeline_apply(make_stage_fn(cfg), params["blocks"], x_mb, mesh)
    y = unmicrobatch(y)
    y = _ln(y, params["final_ln_scale"], params["final_ln_bias"])
    return y @ params["embed"].T.astype(jnp.float32) + params["head_bias"]


def make_init_fn(cfg: PipelinedLMConfig):
    def init_fn(rng):
        return init_params(rng, cfg), {}

    return init_fn


def lm_loss_fn(cfg: PipelinedLMConfig, mesh):
    """Engine LossFn: next-token loss. Batch {"input_ids" [B, S]}."""

    def loss_fn(params, model_state, batch, rng):
        del rng  # deterministic
        ids = batch["input_ids"]
        logits = apply(params, ids, cfg, mesh)
        labels = jnp.concatenate(
            [ids[:, 1:], jnp.full_like(ids[:, :1], IGNORE_INDEX)], axis=1
        )
        loss, acc = _masked_xent(logits, labels)
        return loss, (model_state, {"accuracy": acc})

    return loss_fn


def reference_apply(params, input_ids, cfg: PipelinedLMConfig):
    """Sequential (no-pipeline) oracle for tests: same params, same math,
    plain scan over all S·Lps layers."""
    dtype = jnp.dtype(cfg.dtype)
    B, S = input_ids.shape
    x = params["embed"][input_ids] + params["pos"][None, :S]
    x = x.astype(dtype)
    flat = jax.tree.map(
        lambda p: p.reshape(p.shape[0] * p.shape[1], *p.shape[2:]),
        params["blocks"],
    )

    def layer(x, p):
        return _block_apply(p, x, cfg), None

    x, _ = jax.lax.scan(layer, x, flat)
    x = _ln(x, params["final_ln_scale"], params["final_ln_bias"])
    return x @ params["embed"].T.astype(jnp.float32) + params["head_bias"]
