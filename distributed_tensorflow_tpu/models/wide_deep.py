"""Wide & Deep recommender (BASELINE.json:11) — embedding-parallel, TPU-first.

Reference analog (SURVEY.md §2a 'Model fns', §2c 'Embedding parallel'): a
wide linear path over sparse crosses plus a deep MLP over embeddings, with
the big tables living on parameter servers as sparse variables
(round-robin via device_setter.py:147-149; sparse sync gradients through
SparseConditionalAccumulator, data_flow_ops.py:1478). The substrate's TPU
answer is TPUEmbedding ($TF/python/tpu/tpu_embedding_v2.py:76).

TPU-first choices:

- **Tables sharded by layout**: each categorical feature's [V, D] table is
  a plain flax param; ``embedding_rules()`` vocab-shards it over the
  ``model`` axis (P('model', None)) and GSPMD turns ``jnp.take`` into the
  gather + collective exchange — zero model code knows about placement
  (same design as transformer.py TP).
- **Explicit-collective option**: ``embed_impl='explicit'`` routes lookups
  through ops/embedding.py's *range*-sharded shard_map path — the
  hand-written exchange (owned-gather + psum) over the same P('model',
  None) layout GSPMD gives the param, so no re-layout; parity is tested
  against the take path. (The mod-sharded variant for hot-id balancing
  lives in ops/embedding.py too, with its own layout.)
- **Dense gradients**: on TPU the IndexedSlices/sparse-accumulator
  machinery disappears — table grads are dense scatter-adds inside the one
  compiled step, aggregated by the same psum as every other grad.
- **Wide weights folded into the tables**: each table is [V, D+1]; the last
  column is the per-id wide (linear) weight, zero-init. One lookup per
  feature serves both paths — half the model-axis exchanges of separate
  wide tables (the `tf.feature_column` linear path without the vocabulary
  plumbing, fused).

Batch contract: {"cat": (B, F) int32, "dense": (B, Dd) float32,
"label": (B,) float in {0,1}} — F categorical features, Dd dense features.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from ..parallel import mesh as mesh_lib
from ..parallel import sharding
from ..utils import metrics as metrics_lib


@dataclasses.dataclass(frozen=True)
class WideDeepConfig:
    # multiples of 8 so vocab dims shard evenly over any test mesh axis
    vocab_sizes: tuple[int, ...] = (1024, 1024, 512, 128, 64)
    embed_dim: int = 32
    dense_features: int = 13
    hidden_sizes: tuple[int, ...] = (256, 128, 64)
    dropout: float = 0.0
    dtype: str = "bfloat16"
    # "take": plain jnp.take, sharding by layout (GSPMD inserts comms).
    # "explicit": ops/embedding.py range-sharded shard_map lookup.
    embed_impl: str = "take"


#: Coverage fixture: the default WideDeepConfig's param tree (5 vocab
#: features, 3 hidden layers), fully literal so the dtflint
#: shard-rules-coverage rule reads it statically — pinned to the live
#: model by tests/test_sharding.py::test_wide_deep_coverage_fixture_is_live.
_WIDE_DEEP_COVERAGE = (
    "deep_0/bias", "deep_0/kernel", "deep_1/bias", "deep_1/kernel",
    "deep_2/bias", "deep_2/kernel", "deep_out/bias", "deep_out/kernel",
    "table_0", "table_1", "table_2", "table_3", "table_4",
    "wide_dense/bias", "wide_dense/kernel",
    "wide_table_0", "wide_table_1", "wide_table_2", "wide_table_3",
    "wide_table_4",
)

#: Partition-rules table: vocab-shard every table (deep embeddings AND
#: wide linear columns) over `model`; the MLP is declared replicated
#: (recommender MLPs are small — DP/fsdp handles them). Patterns are
#: segment-anchored: the engine's dead-rule check exposed that the old
#: un-anchored ``table_\d+`` row also swallowed every ``wide_table_``
#: param, leaving the wide row permanently dead (same spec, so no
#: behavior change — but a rotted rule all the same).
WIDE_DEEP_RULES = sharding.partition_rules(
    "wide-deep",
    (
        (r"(^|/)table_\d+$", P(mesh_lib.MODEL, None)),
        (r"(^|/)wide_table_\d+$", P(mesh_lib.MODEL, None)),
        (sharding.CATCH_ALL, sharding.REPLICATED),
    ),
    coverage=_WIDE_DEEP_COVERAGE,
)


def embedding_rules() -> list[tuple[str, P]]:
    """Legacy soft form of :data:`WIDE_DEEP_RULES` (the two table rows,
    replicate-on-miss) — pre-engine call sites; the shipped workload
    passes the table itself."""
    return [(r.pattern, r.spec) for r in WIDE_DEEP_RULES.rows
            if r.pattern != sharding.CATCH_ALL]


class WideDeep(nn.Module):
    cfg: WideDeepConfig
    mesh: Any = None  # required only for embed_impl='explicit'

    @nn.compact
    def __call__(self, cat, dense, *, train: bool = False):
        cfg = self.cfg
        dtype = jnp.dtype(cfg.dtype)
        n_feat = len(cfg.vocab_sizes)
        assert cat.shape[-1] == n_feat, (cat.shape, n_feat)

        # Deep embedding tables and the wide linear weights are SEPARATE
        # params (table_i [v, embed_dim] / wide_table_i [v, 1]): the
        # reference trains the sparse wide weights with FTRL and the deep
        # tables with AdaGrad (DNNLinearCombinedClassifier defaults), and
        # optimizer grouping is per-leaf (workloads/wide_deep.py
        # _canonical_tx) — a packed [v, embed_dim+1] table could not split.
        tables = [
            self.param(
                f"table_{i}",
                nn.initializers.normal(stddev=1.0 / jnp.sqrt(cfg.embed_dim)),
                (v, cfg.embed_dim), jnp.float32,
            )
            for i, v in enumerate(cfg.vocab_sizes)
        ]
        wide_tables = [
            # zeros, like the reference's linear path
            self.param(f"wide_table_{i}", nn.initializers.zeros, (v, 1),
                       jnp.float32)
            for i, v in enumerate(cfg.vocab_sizes)
        ]

        lookup = self._make_lookup()
        embeds = [
            lookup(cat[..., i], t).astype(dtype)
            for i, t in enumerate(tables)
        ]
        wide_logit = sum(
            lookup(cat[..., i], t)[..., 0].astype(jnp.float32)
            for i, t in enumerate(wide_tables)
        )
        wide_logit = wide_logit + nn.Dense(
            1, dtype=jnp.float32, name="wide_dense"
        )(dense)[..., 0]

        h = jnp.concatenate(embeds + [dense.astype(dtype)], axis=-1)
        for j, width in enumerate(cfg.hidden_sizes):
            h = nn.Dense(width, dtype=dtype, name=f"deep_{j}")(h)
            h = nn.relu(h)
            if cfg.dropout > 0:
                h = nn.Dropout(cfg.dropout, deterministic=not train)(h)
        deep_logit = nn.Dense(1, dtype=jnp.float32, name="deep_out")(h)[..., 0]
        return wide_logit + deep_logit

    def _make_lookup(self):
        if self.cfg.embed_impl == "take":
            return lambda ids, table: jnp.take(table, ids, axis=0)
        if self.cfg.embed_impl == "explicit":
            from ..ops import embedding as emb

            if self.mesh is None or self.mesh.shape[mesh_lib.MODEL] == 1:
                # degrade gracefully: mod-sharding over a size-1 axis is take
                return lambda ids, table: jnp.take(table, ids, axis=0)

            # Table params are laid out P(model, None) by embedding_rules —
            # range sharding — which the range kernel consumes with zero
            # re-layout.
            return emb.make_range_sharded_lookup(self.mesh, mesh_lib.MODEL)
        raise ValueError(f"Unknown embed_impl {self.cfg.embed_impl!r}")


def make_init_fn(cfg: WideDeepConfig, mesh=None):
    # Init twin with the plain-take lookup: param shapes are impl-independent,
    # and the twin avoids tracing shard_map with the size-1 dummy batch
    # (same trick as transformer.make_init_fn).
    del mesh
    model = WideDeep(dataclasses.replace(cfg, embed_impl="take"))

    def init_fn(rng):
        cat = jnp.zeros((1, len(cfg.vocab_sizes)), jnp.int32)
        dense = jnp.zeros((1, cfg.dense_features), jnp.float32)
        variables = model.init({"params": rng, "dropout": rng}, cat, dense)
        variables = dict(variables)
        return variables.pop("params"), variables

    return init_fn


def ctr_loss_fn(model: WideDeep):
    """Binary cross-entropy on click logits + AUC-proxy accuracy."""

    def loss_fn(params, model_state, batch, rng):
        logits = model.apply(
            {"params": params, **model_state},
            batch["cat"], batch["dense"], train=True, rngs={"dropout": rng},
        )
        labels = batch["label"].astype(jnp.float32)
        loss = optax.sigmoid_binary_cross_entropy(logits, labels).mean()
        acc = jnp.mean(((logits > 0) == (labels > 0.5)).astype(jnp.float32))
        return loss, (model_state, {"accuracy": acc})

    return loss_fn


def ctr_eval_fn(model: WideDeep):
    """Summed eval stats + streaming-AUC histograms (the reference's CTR
    metric of record: $TF/python/ops/metrics_impl.py:809 tf.metrics.auc;
    see utils/metrics.py for the mergeable-histogram formulation)."""

    def eval_fn(params, model_state, batch):
        logits = model.apply(
            {"params": params, **model_state}, batch["cat"], batch["dense"]
        )
        labels = batch["label"].astype(jnp.float32)
        loss = optax.sigmoid_binary_cross_entropy(logits, labels).sum()
        correct = jnp.sum(((logits > 0) == (labels > 0.5)).astype(jnp.float32))
        return {
            "loss_sum": loss,
            "correct": correct,
            "count": jnp.asarray(labels.shape[0], jnp.float32),
            **metrics_lib.auc_histograms(logits, labels),
        }

    return eval_fn


def flops_per_example(cfg: WideDeepConfig) -> float:
    """Analytic FORWARD FLOPs (MFU accounting, SURVEY.md §5.5; framework
    contract: fwd-only, see utils/flops.py). Embedding gathers are
    bandwidth, not FLOPs; count the MLP matmuls."""
    d_in = len(cfg.vocab_sizes) * cfg.embed_dim + cfg.dense_features
    flops = 0.0
    prev = d_in
    for w in cfg.hidden_sizes:
        flops += 2.0 * prev * w
        prev = w
    flops += 2.0 * prev  # deep_out
    flops += 2.0 * cfg.dense_features  # wide_dense
    return flops
