"""Model zoo — the five BASELINE.json workload families, flax-native.

Reference analog: per-script raw-TF model fns (SURVEY.md §2a). Each module
ships the flax Module, a config dataclass, and analytic FLOPs for MFU
accounting (utils/flops.py)."""

from . import common  # noqa: F401
from .mlp import MLP, MLPConfig  # noqa: F401
from .cnn import CNN, CNNConfig  # noqa: F401
from .resnet import ResNet, ResNet50, ResNetConfig  # noqa: F401
from .wide_deep import WideDeep, WideDeepConfig  # noqa: F401
from .transformer import (  # noqa: F401
    Transformer,
    TransformerConfig,
    bert_base,
    gpt_small,
)
