"""CIFAR-10 CNN — BASELINE.json:8 workload 2 (sync data-parallel ×8).

The reference ran this as the canonical SyncReplicasOptimizer demo; here
the same capability is the GSPMD data axis. Architecture: simple
conv-bn-relu stack (BN becomes cross-replica BN for free under GSPMD —
models/common.py note)."""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class CNNConfig:
    channels: tuple = (32, 64, 128)
    dense_size: int = 256
    num_classes: int = 10
    dropout_rate: float = 0.0
    use_batchnorm: bool = True
    dtype: str = "float32"


class CNN(nn.Module):
    cfg: CNNConfig

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        dtype = jnp.dtype(self.cfg.dtype)
        x = x.astype(dtype)
        for i, ch in enumerate(self.cfg.channels):
            x = nn.Conv(ch, (3, 3), dtype=dtype, name=f"conv_{i}")(x)
            if self.cfg.use_batchnorm:
                x = nn.BatchNorm(
                    use_running_average=not train, dtype=dtype,
                    momentum=0.9, name=f"bn_{i}",
                )(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape(x.shape[0], -1)
        x = nn.Dense(self.cfg.dense_size, dtype=dtype, name="dense")(x)
        x = nn.relu(x)
        if self.cfg.dropout_rate > 0:
            x = nn.Dropout(self.cfg.dropout_rate, deterministic=not train)(x)
        return nn.Dense(self.cfg.num_classes, dtype=dtype, name="head")(x)


def flops_per_example(cfg: CNNConfig, image_size: int = 32) -> float:
    """Forward FLOPs (framework contract: fwd-only, see utils/flops.py)."""
    fwd = 0.0
    h = image_size
    in_c = 3
    for ch in cfg.channels:
        fwd += 2.0 * h * h * ch * in_c * 9  # 3x3 conv at same resolution
        h //= 2
        in_c = ch
    fwd += 2.0 * (h * h * in_c) * cfg.dense_size
    fwd += 2.0 * cfg.dense_size * cfg.num_classes
    return fwd
