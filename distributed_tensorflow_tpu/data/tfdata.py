"""tf.data adapter: drive the host-batch contract from a tf.data.Dataset.

SURVEY.md §7 keeps tf.data as the input-pipeline *option* (the reference's
own input path was per-worker ``tf.data`` with
``Dataset.shard(num_workers, task_index)``, §2a). This adapter maps that
world onto this framework's contract — an iterable of per-host numpy dict
batches of size ``global_batch / process_count`` (data/pipeline.py) — so
existing tf.data input pipelines (TFRecord readers, tf.image augmentation,
interleave trees) port without rewriting:

    parts = WorkloadParts(...,
        dataset_fn=lambda start: tfdata.host_stream(
            make_ds, cfg.data.global_batch_size, start_index=start),
    )

TensorFlow is imported lazily — the framework never requires it unless
this adapter is used.
"""

from __future__ import annotations

from typing import Any, Callable, Iterator

import jax
import numpy as np

from .pipeline import local_batch_size


def shard_for_host(ds):
    """The `Dataset.shard(num_workers, task_index)` of the reference,
    keyed by JAX process topology: each host reads a disjoint 1/n slice.
    Apply at the FILE or example level, before batching."""
    return ds.shard(jax.process_count(), jax.process_index())


def host_stream(
    make_dataset: Callable[[], Any],
    global_batch_size: int,
    *,
    start_index: int = 0,
    shuffle_buffer: int = 0,
    seed: int = 0,
    repeat: bool = True,
    shard: bool = True,
) -> Iterator[dict]:
    """Element-level tf.data factory -> per-host numpy dict batch stream.

    make_dataset: returns an UNBATCHED tf.data.Dataset of dict elements
        (e.g. {"image": ..., "label": ...}). Called once per stream.
    start_index: number of BATCHES to skip — the resume offset the runner
        passes (workloads/runner.py calls dataset_fn(start_step)).
    shuffle_buffer: >0 enables per-epoch shuffling with a per-host seed
        (disjoint host slices stay disjoint).
    shard: set False when make_dataset already shards per host (a ported
        pipeline with its own Dataset.shard, or file-level shard_for_host
        inside the factory) — sharding twice would silently drop data.
    """
    import tensorflow as tf  # lazy: only adapter users need TF

    local_bs = local_batch_size(global_batch_size)
    ds = make_dataset()
    if shard:
        ds = shard_for_host(ds)
    if shuffle_buffer > 0:
        # shuffle BEFORE repeat so each epoch reshuffles and epoch
        # boundaries aren't blended through the buffer
        ds = ds.shuffle(
            shuffle_buffer, seed=seed * 1_000_003 + jax.process_index(),
            reshuffle_each_iteration=True,
        )
    if repeat:
        ds = ds.repeat()
    ds = ds.batch(local_bs, drop_remainder=True)
    if start_index:
        ds = ds.skip(start_index)
    ds = ds.prefetch(tf.data.AUTOTUNE)
    for elem in ds.as_numpy_iterator():
        yield {k: np.asarray(v) for k, v in elem.items()}
