"""Text streams for LM/MLM workloads: synthetic learnable corpora + token
file reader.

Reference analog: the BERT config's TFRecord input pipeline
(SURVEY.md §2a 'Input pipeline' row; BASELINE.json:10). Per-host disjoint
slices follow the same seeding discipline as pipeline.py; batches are
numpy dicts that Trainer.put_batch assembles into global sharded arrays.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator

import numpy as np

from .pipeline import batch_rng, local_batch_size

MASK_FRACTION_KEEP = 0.1  # BERT 80/10/10 corruption split
MASK_FRACTION_RANDOM = 0.1
IGNORE_INDEX = -100


@dataclasses.dataclass(frozen=True)
class TextDataConfig:
    dataset: str = "synthetic_mlm"  # synthetic_mlm | synthetic_lm | tokens:<path.npy>
    global_batch_size: int = 256
    seq_len: int = 128
    vocab_size: int = 30528
    mask_prob: float = 0.15
    seed: int = 0
    mask_token: int = 103  # [MASK] in BERT vocab
    # > 0: emit the gathered-head MLM format — exactly this many
    # prediction positions per example as "masked_positions" [B,K] +
    # "masked_labels" [B,K] (the reference's masked_lm_positions /
    # max_predictions_per_seq shape) instead of dense [B,S] labels.
    # The model then runs its MLM head + vocab projection on [B,K,d]
    # (models/transformer.Transformer positions docstring). 0 keeps the
    # dense-labels format; -1 = auto: round(mask_prob * seq_len).
    max_predictions: int = 0


def resolved_max_predictions(cfg: TextDataConfig) -> int:
    """0 = dense labels; -1 = auto (round(mask_prob * seq_len)); else the
    explicit count. Single definition shared by the dataset and the
    workloads' FLOPs accounting."""
    if cfg.max_predictions == 0:
        return 0
    K = (max(1, int(round(cfg.mask_prob * cfg.seq_len)))
         if cfg.max_predictions < 0 else cfg.max_predictions)
    if K > cfg.seq_len:
        raise ValueError(f"max_predictions={K} > seq_len={cfg.seq_len}")
    return K


def mlm_mask_batch(tokens: np.ndarray, cfg: TextDataConfig,
                   rng: np.random.RandomState) -> dict[str, np.ndarray]:
    """BERT-style MLM corruption of a [B, S] token batch: 80% [MASK] /
    10% random / 10% keep, emitting either the gathered-head format
    (masked_positions/masked_labels, the reference's masked_lm_positions
    shape) or dense [B, S] labels with IGNORE_INDEX, per
    ``resolved_max_predictions``. One definition shared by the synthetic
    and real-corpus (token-file) MLM streams."""
    K = resolved_max_predictions(cfg)
    if K > 0:
        # gathered-head format: exactly K positions per example,
        # sampled without replacement (argsort of uniform noise)
        positions = np.argsort(
            rng.rand(*tokens.shape), axis=1
        )[:, :K].astype(np.int32)
        positions.sort(axis=1)
        masked = np.zeros(tokens.shape, bool)
        np.put_along_axis(masked, positions, True, axis=1)
    else:
        masked = rng.rand(*tokens.shape) < cfg.mask_prob
    u = rng.rand(*tokens.shape)
    inputs = tokens.copy()
    # 80% -> [MASK], 10% -> random token, 10% -> keep
    inputs[masked & (u < 0.8)] = cfg.mask_token
    rand_tok = rng.randint(0, cfg.vocab_size, tokens.shape)
    inputs[masked & (u >= 0.8) & (u < 0.9)] = rand_tok[
        masked & (u >= 0.8) & (u < 0.9)
    ]
    if K > 0:
        return {
            "input_ids": inputs.astype(np.int32),
            "masked_positions": positions,
            "masked_labels": np.take_along_axis(
                tokens, positions, axis=1).astype(np.int32),
        }
    labels = np.where(masked, tokens, IGNORE_INDEX)
    return {
        "input_ids": inputs.astype(np.int32),
        "labels": labels.astype(np.int32),
    }


class SyntheticMLM:
    """Learnable synthetic MLM: positions alternate (free, determined) —
    token at odd index = perm[token at even index]. A masked odd token is
    recoverable from its left neighbor, a masked even one from its right
    neighbor via the inverse permutation, so MLM accuracy has real headroom
    (≈1.0 achievable) and convergence tests are meaningful — the text analog
    of pipeline.SyntheticClassification's linear teacher."""

    def __init__(self, cfg: TextDataConfig, num_batches: int | None = None,
                 index_offset: int = 0):
        self.cfg = cfg
        self.num_batches = num_batches
        self.index_offset = index_offset
        self.local_bs = local_batch_size(cfg.global_batch_size)
        rng = np.random.RandomState(cfg.seed)
        self.perm = rng.permutation(cfg.vocab_size)

    def _tokens(self, rng: np.random.RandomState) -> np.ndarray:
        cfg = self.cfg
        half = (cfg.seq_len + 1) // 2
        even = rng.randint(0, cfg.vocab_size, (self.local_bs, half))
        odd = self.perm[even]
        seq = np.empty((self.local_bs, half * 2), np.int64)
        seq[:, 0::2] = even
        seq[:, 1::2] = odd
        return seq[:, : cfg.seq_len]

    def batch(self, index: int) -> dict[str, np.ndarray]:
        cfg = self.cfg
        index += self.index_offset
        rng = batch_rng(cfg.seed, index)
        tokens = self._tokens(rng)
        return mlm_mask_batch(tokens, cfg, rng)

    def __iter__(self) -> Iterator[dict[str, np.ndarray]]:
        i = 0
        while self.num_batches is None or i < self.num_batches:
            yield self.batch(i)
            i += 1


class SyntheticLM:
    """Learnable causal stream: first token free, then a noisy deterministic
    walk t[i+1] = perm[t[i]] (with ``noise`` chance of a uniform resample) —
    next-token accuracy converges toward 1-noise."""

    def __init__(self, cfg: TextDataConfig, num_batches: int | None = None,
                 index_offset: int = 0, noise: float = 0.05):
        self.cfg = cfg
        self.num_batches = num_batches
        self.index_offset = index_offset
        self.noise = noise
        self.local_bs = local_batch_size(cfg.global_batch_size)
        rng = np.random.RandomState(cfg.seed)
        self.perm = rng.permutation(cfg.vocab_size)

    def batch(self, index: int) -> dict[str, np.ndarray]:
        cfg = self.cfg
        index += self.index_offset
        rng = batch_rng(cfg.seed, index)
        seq = np.empty((self.local_bs, cfg.seq_len), np.int64)
        seq[:, 0] = rng.randint(0, cfg.vocab_size, self.local_bs)
        for i in range(1, cfg.seq_len):
            step = self.perm[seq[:, i - 1]]
            resample = rng.rand(self.local_bs) < self.noise
            seq[:, i] = np.where(
                resample, rng.randint(0, cfg.vocab_size, self.local_bs), step
            )
        return {"input_ids": seq.astype(np.int32)}

    def __iter__(self) -> Iterator[dict[str, np.ndarray]]:
        i = 0
        while self.num_batches is None or i < self.num_batches:
            yield self.batch(i)
            i += 1


class TokenFileLM:
    """Causal LM batches over a flat token array (.npy of int32 ids) — the
    hook for real corpora tokenized offline. Per-host disjoint strided
    windows; index_offset resumes the stream."""

    def __init__(self, path: str, cfg: TextDataConfig,
                 num_batches: int | None = None, index_offset: int = 0):
        self.tokens = np.load(path, mmap_mode="r")
        self.cfg = cfg
        self.num_batches = num_batches
        self.index_offset = index_offset
        self.local_bs = local_batch_size(cfg.global_batch_size)

    def _windows(self, index: int) -> np.ndarray:
        """[local_bs, seq_len] token windows for global batch ``index``.

        The RNG here is deliberately host-AGREED (seed+index, no process
        fold): every host draws the same global start list and takes its
        disjoint stride slice — the per-host disjointness lives in the
        slicing, not the seed."""
        import jax

        cfg = self.cfg
        rng = np.random.RandomState((cfg.seed + index) & 0x7FFFFFFF)
        n_windows = (len(self.tokens) - 1) // cfg.seq_len
        starts = rng.randint(0, n_windows, self.local_bs * jax.process_count())
        starts = starts[jax.process_index():: jax.process_count()] * cfg.seq_len
        return np.stack([self.tokens[s : s + cfg.seq_len] for s in starts])

    def batch(self, index: int) -> dict[str, np.ndarray]:
        index += self.index_offset
        return {"input_ids": self._windows(index).astype(np.int32)}

    def __iter__(self):
        i = 0
        while self.num_batches is None or i < self.num_batches:
            yield self.batch(i)
            i += 1


class TokenFileMLM(TokenFileLM):
    """MLM batches over a real tokenized corpus — the reference BERT's
    TFRecord masked_lm_positions pipeline, rebuilt over a flat .npy token
    file (tools/make_token_file.py converts raw text offline). Window
    sampling is TokenFileLM's; corruption and output format (gathered
    positions or dense labels) are the shared ``mlm_mask_batch``."""

    def batch(self, index: int) -> dict[str, np.ndarray]:
        index += self.index_offset
        tokens = self._windows(index).astype(np.int64)
        # masking noise must be host-DISJOINT (unlike the window draws):
        # batch_rng folds process_index so each host corrupts its slice
        # independently — the pipeline.py seeding discipline
        return mlm_mask_batch(tokens, self.cfg,
                              batch_rng(self.cfg.seed, index))


def make_text_dataset(cfg: TextDataConfig, num_batches: int | None = None,
                      index_offset: int = 0):
    if cfg.dataset == "synthetic_mlm":
        return SyntheticMLM(cfg, num_batches, index_offset)
    if cfg.dataset == "synthetic_lm":
        return SyntheticLM(cfg, num_batches, index_offset)
    if cfg.dataset.startswith("tokens:"):
        return TokenFileLM(cfg.dataset[7:], cfg, num_batches, index_offset)
    if cfg.dataset.startswith("tokens_mlm:"):
        return TokenFileMLM(cfg.dataset[11:], cfg, num_batches,
                            index_offset)
    raise ValueError(f"Unknown text dataset '{cfg.dataset}'")
