"""JPEG record container + decode/augment dataset — the real-ImageNet path.

The reference consumed per-worker TFRecords of JPEG bytes and decoded with
tf.data on each worker's host (SURVEY.md §2a 'Input pipeline'). Here the
container is two flat files the host can mmap:

- ``<path>.dat`` — concatenated raw JPEG streams
- ``<path>.idx`` — N × [u64 offset, u64 length, i64 label] little-endian

Fixed 24-byte index entries make sharding/shuffling O(1) per record with
no per-record framing in the data file (same design driver as the dense
record loader, data/records.py). Decode + random-resized-crop/flip
augmentation run on the host and overlap device compute through the
Prefetcher. Two decode tiers share one augmentation policy
(augment.sample_crop_rect): the native C++ libjpeg stage
(native/dtf_jpeg.cpp via data/native_jpeg.py — DCT-domain downscaled
decode, threaded; the default when it builds) and a PIL thread pool
fallback.
"""

from __future__ import annotations

import concurrent.futures as cf
import os

import numpy as np

from . import augment

_ENTRY = np.dtype([("offset", "<u8"), ("length", "<u8"), ("label", "<i8")])


def make_jpeg_record_file(
    path: str, images: np.ndarray, labels: np.ndarray, *, quality: int = 90
) -> int:
    """Encode [N, H, W, 3] uint8 images as JPEGs into <path>.dat/.idx
    (test/tooling path — real datasets are converted offline). Returns N."""
    import io

    from PIL import Image

    entries = np.empty(len(images), _ENTRY)
    with open(path + ".dat", "wb") as f:
        off = 0
        for i, (img, lab) in enumerate(zip(images, labels)):
            buf = io.BytesIO()
            Image.fromarray(np.asarray(img, np.uint8)).save(
                buf, "JPEG", quality=quality
            )
            raw = buf.getvalue()
            f.write(raw)
            entries[i] = (off, len(raw), int(lab))
            off += len(raw)
    entries.tofile(path + ".idx")
    return len(images)


class JpegClassificationDataset:
    """Iterable of {"image" f32 [B,S,S,3] in [0,1], "label" i32 [B]}
    batches from a JPEG record pair. Per-host sharded (strided over the
    epoch shuffle, like NpzDataset), resumable via ``index_offset``,
    decode+augment parallel across a thread pool.

    ``train=True``: random-resized-crop to ``image_size`` + horizontal
    flip (the ImageNet recipe); ``train=False``: resize + center crop.
    """

    def __init__(self, path: str, image_size: int, global_batch_size: int,
                 *, seed: int = 0, train: bool = True,
                 num_batches: int | None = None, index_offset: int = 0,
                 n_threads: int | None = None, decoder: str = "auto"):
        import jax

        from .pipeline import local_batch_size

        self.path = path
        self.image_size = image_size
        self.seed = seed
        self.train = train
        self.num_batches = num_batches
        self.index_offset = index_offset
        self.local_bs = local_batch_size(global_batch_size)
        self.entries = np.fromfile(path + ".idx", _ENTRY)
        if not len(self.entries):
            raise ValueError(f"{path}.idx is empty")
        self._data = np.memmap(path + ".dat", np.uint8, "r")
        self._shard = jax.process_index()
        self._n_shards = jax.process_count()
        self._n_threads = n_threads or min(16, os.cpu_count() or 4)
        # decoder: "native" = C++ libjpeg stage (native/dtf_jpeg.cpp —
        # DCT-downscaled decode + crop + bilinear, threaded); "pil" =
        # Python/PIL in a thread pool; "auto" = native when the library
        # builds (DTF_JPEG_DECODER env overrides). The two decoders draw
        # IDENTICAL crop/flip decisions (augment.sample_crop_rect is the
        # one policy definition) but resample with different filters, so
        # pixels differ slightly; each is deterministic for resume.
        decoder = os.environ.get("DTF_JPEG_DECODER", decoder)
        if decoder not in ("auto", "pil", "native"):
            raise ValueError(f"unknown decoder {decoder!r}")
        if decoder == "auto":
            from . import native_jpeg

            decoder = "native" if native_jpeg.available() else "pil"
        elif decoder == "native":
            from . import native_jpeg

            if not native_jpeg.available():
                raise RuntimeError(
                    "decoder='native' requested but native/dtf_jpeg.cpp "
                    "did not build (g++ or libjpeg missing)")
        self.decoder = decoder
        self._pool = (
            cf.ThreadPoolExecutor(max_workers=self._n_threads)
            if decoder == "pil" else None
        )

    def _batches_per_epoch(self) -> int:
        n = len(self.entries) // self._n_shards
        return max(n // self.local_bs, 1)

    def _decode_one(self, entry, rng_seed: int) -> np.ndarray:
        import io

        from PIL import Image

        raw = self._data[entry["offset"]: entry["offset"] + entry["length"]]
        img = np.asarray(Image.open(io.BytesIO(raw.tobytes())).convert("RGB"))
        rng = np.random.RandomState(rng_seed & 0x7FFFFFFF)
        if self.train:
            img = augment.random_resized_crop(img, rng, self.image_size)
            img = augment.hflip(img, rng)
        else:
            img = augment.resize_center_crop(img, self.image_size)
        return img

    def _decode_batch_native(self, entries, seeds) -> np.ndarray:
        """C++ decode stage: Python samples the SAME crop/flip decisions
        as the PIL path (augment.sample_crop_rect / hflip draw order),
        the native library executes decode+crop+resize."""
        from . import native_jpeg

        n = len(entries)
        dims = native_jpeg.jpeg_dims(
            self._data, entries["offset"], entries["length"])
        rects = np.empty((n, 4), np.int64)
        flips = np.zeros(n, bool)
        for i in range(n):
            h, w = int(dims[i, 0]), int(dims[i, 1])
            if h == 0 or w == 0:  # unparsable; decode will zero-fill
                rects[i] = (0, 0, 1, 1)
                continue
            if self.train:
                rng = np.random.RandomState(seeds[i] & 0x7FFFFFFF)
                rects[i] = augment.sample_crop_rect(h, w, rng)
                flips[i] = rng.rand() < 0.5
            else:
                # resize_center_crop equivalence: centered square of
                # side short*0.875, resized to image_size
                side = max(1, int(round(min(h, w) * 0.875)))
                rects[i] = ((h - side) // 2, (w - side) // 2, side, side)
        out = native_jpeg.decode_crop_resize(
            self._data, entries["offset"], entries["length"], rects,
            self.image_size, self._n_threads,
        )
        if flips.any():
            out[flips] = out[flips, :, ::-1]
        return out

    def batch(self, index: int) -> dict[str, np.ndarray]:
        index += self.index_offset
        bpe = self._batches_per_epoch()
        epoch, pos = divmod(index, bpe)
        order = np.arange(len(self.entries))
        if self.train:
            np.random.RandomState(self.seed + epoch).shuffle(order)
        order = order[self._shard:: self._n_shards]
        idx = order[pos * self.local_bs: (pos + 1) * self.local_bs]
        entries = self.entries[idx]
        # per-image seeds: deterministic in (seed, global batch index, slot)
        seeds = [
            (self.seed * 1_000_003 + index) * 131 + int(i) for i in idx
        ]
        if self.decoder == "native":
            img = self._decode_batch_native(entries, seeds).astype(np.float32)
        else:
            images = list(self._pool.map(self._decode_one, entries, seeds))
            img = np.stack(images).astype(np.float32)
        img *= 1.0 / 255.0
        return {
            "image": img,
            "label": entries["label"].astype(np.int32),
        }

    def __iter__(self):
        i = 0
        while self.num_batches is None or i < self.num_batches:
            yield self.batch(i)
            i += 1
