"""Classification datasets over flat record files via the native loader.

Record layout: ``H·W·C uint8 image bytes ++ 4-byte LE int32 label`` — the
dense-file analog of TFRecord for fixed-shape examples, chosen so the
native loader (runtime/loader.py) can mmap + memcpy without per-record
parsing. `make_record_file` writes one from arrays (test/tooling path).

This is the TPU-rate input path for image workloads (SURVEY.md §7 M7
names input starvation the top hard part): C++ worker threads assemble
shard-disjoint shuffled batches; decode here is one vectorized cast.
"""

from __future__ import annotations

import numpy as np

from ..runtime.loader import RecordFileLoader


def make_record_file(path: str, images: np.ndarray, labels: np.ndarray) -> int:
    """Write images [N, ...] uint8 + labels [N] int32 as flat records;
    returns record_bytes."""
    n = images.shape[0]
    img = np.ascontiguousarray(images, np.uint8).reshape(n, -1)
    lab = np.ascontiguousarray(labels, np.int32).reshape(n, 1)
    rec = np.concatenate([img, lab.view(np.uint8)], axis=1)
    rec.tofile(path)
    return rec.shape[1]


class RecordClassificationDataset:
    """Iterable of {"image" f32 [B,*shape] /255, "label" i32 [B]} batches,
    per-host sharded, resumable via ``index_offset`` (the make_dataset
    contract, data/pipeline.py)."""

    def __init__(self, path: str, image_shape: tuple[int, ...],
                 global_batch_size: int, *, seed: int = 0,
                 num_batches: int | None = None, index_offset: int = 0,
                 n_threads: int = 4, use_native: bool | None = None,
                 flat: bool = False, augment: str = "none"):
        import jax

        from .pipeline import local_batch_size

        self.image_shape = tuple(image_shape)
        self.flat = flat  # emit (B, H·W·C) — the DataConfig.flat contract
        if augment not in ("none", "crop_flip"):
            raise ValueError(f"Unknown augment mode {augment!r}")
        if augment == "crop_flip" and (flat or len(image_shape) != 3):
            raise ValueError("crop_flip needs [H, W, C] images (flat=False)")
        self.augment = augment
        self.seed = seed
        img_bytes = int(np.prod(image_shape))
        self.loader = RecordFileLoader(
            path, img_bytes + 4, local_batch_size(global_batch_size),
            seed=seed, shard=jax.process_index(),
            n_shards=jax.process_count(), n_threads=n_threads,
            decode=self._decode, start_batch=index_offset,
            num_batches=num_batches, use_native=use_native,
        )

    def _decode(self, raw: np.ndarray, batch_index: int = 0):
        img = raw[:, :-4]
        if not self.flat:
            img = img.reshape(-1, *self.image_shape)
        if self.augment == "crop_flip":
            # deterministic per (seed, batch index): resume at step N
            # reproduces batch N's augmentation exactly
            from . import augment as aug

            rng = np.random.RandomState(
                (self.seed * 1_000_003 + batch_index) & 0x7FFFFFFF
            )
            img = aug.random_crop_flip(img, rng)
        img = img.astype(np.float32)
        img *= 1.0 / 255.0
        label = raw[:, -4:].copy().view(np.int32)[:, 0]
        return {"image": img, "label": label}

    def __iter__(self):
        return iter(self.loader)
