"""Synthetic CTR stream for the Wide&Deep workload (BASELINE.json:11).

Same design as pipeline.SyntheticClassification: a fixed random teacher
(per-feature embedding tables + linear head) labels clicks, so loss/AUC
curves are meaningful without dataset files; per-host disjoint via
process_index folded into the per-batch seed; Zipf-ish id draws so
mod-sharded tables see realistic hot-id skew (SURVEY.md §7 M9).
"""

from __future__ import annotations

import dataclasses
from typing import Iterator

import numpy as np

from .pipeline import batch_rng, local_batch_size


@dataclasses.dataclass(frozen=True)
class RecsysConfig:
    vocab_sizes: tuple[int, ...] = (1024, 1024, 512, 128, 64)
    dense_features: int = 13
    global_batch_size: int = 256
    teacher_dim: int = 8
    zipf_a: float = 1.3  # id popularity skew
    seed: int = 0


class SyntheticCTR:
    def __init__(self, cfg: RecsysConfig, num_batches: int | None = None,
                 index_offset: int = 0):
        self.cfg = cfg
        self.num_batches = num_batches
        self.index_offset = index_offset
        self.local_bs = local_batch_size(cfg.global_batch_size)
        rng = np.random.RandomState(cfg.seed)
        self.teachers = [
            rng.randn(v, cfg.teacher_dim).astype(np.float32) * 0.5
            for v in cfg.vocab_sizes
        ]
        self.head = rng.randn(
            len(cfg.vocab_sizes) * cfg.teacher_dim + cfg.dense_features
        ).astype(np.float32)

    def batch(self, index: int) -> dict[str, np.ndarray]:
        index += self.index_offset
        rng = batch_rng(self.cfg.seed, index)
        cfg = self.cfg
        b = self.local_bs
        cat = np.stack(
            [
                np.minimum(rng.zipf(cfg.zipf_a, size=b) - 1, v - 1)
                for v in cfg.vocab_sizes
            ],
            axis=-1,
        ).astype(np.int32)
        dense = rng.randn(b, cfg.dense_features).astype(np.float32)
        feats = np.concatenate(
            [t[cat[:, i]] for i, t in enumerate(self.teachers)] + [dense],
            axis=-1,
        )
        score = feats @ self.head
        label = (score > 0).astype(np.float32)  # stationary teacher threshold
        return {"cat": cat, "dense": dense, "label": label}

    def __iter__(self) -> Iterator[dict[str, np.ndarray]]:
        i = 0
        while self.num_batches is None or i < self.num_batches:
            yield self.batch(i)
            i += 1
