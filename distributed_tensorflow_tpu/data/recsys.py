"""CTR streams for the Wide&Deep workload (BASELINE.json:11).

- SyntheticCTR: same design as pipeline.SyntheticClassification — a
  fixed random teacher (per-feature embedding tables + linear head)
  labels clicks, so loss/AUC curves are meaningful without dataset
  files; per-host disjoint via process_index folded into the per-batch
  seed; Zipf-ish id draws so mod-sharded tables see realistic hot-id
  skew (SURVEY.md §7 M9).
- CTRRecordDataset: real data over fixed-size binary records
  (label f32 | dense f32xD | cat i32xF per record) riding the NATIVE
  record loader (runtime/loader.py — threaded shuffle/shard/assembly in
  C++ with the bit-identical Python fallback). tools/make_ctr_records.py
  converts Criteo-format TSV into this layout; this is the reference
  Wide&Deep's real-CTR input path, PS-free.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator

import numpy as np

from .pipeline import batch_rng, local_batch_size


@dataclasses.dataclass(frozen=True)
class RecsysConfig:
    vocab_sizes: tuple[int, ...] = (1024, 1024, 512, 128, 64)
    dense_features: int = 13
    global_batch_size: int = 256
    teacher_dim: int = 8
    zipf_a: float = 1.3  # id popularity skew
    seed: int = 0


class SyntheticCTR:
    def __init__(self, cfg: RecsysConfig, num_batches: int | None = None,
                 index_offset: int = 0):
        self.cfg = cfg
        self.num_batches = num_batches
        self.index_offset = index_offset
        self.local_bs = local_batch_size(cfg.global_batch_size)
        rng = np.random.RandomState(cfg.seed)
        self.teachers = [
            rng.randn(v, cfg.teacher_dim).astype(np.float32) * 0.5
            for v in cfg.vocab_sizes
        ]
        self.head = rng.randn(
            len(cfg.vocab_sizes) * cfg.teacher_dim + cfg.dense_features
        ).astype(np.float32)

    def batch(self, index: int) -> dict[str, np.ndarray]:
        index += self.index_offset
        rng = batch_rng(self.cfg.seed, index)
        cfg = self.cfg
        b = self.local_bs
        cat = np.stack(
            [
                np.minimum(rng.zipf(cfg.zipf_a, size=b) - 1, v - 1)
                for v in cfg.vocab_sizes
            ],
            axis=-1,
        ).astype(np.int32)
        dense = rng.randn(b, cfg.dense_features).astype(np.float32)
        feats = np.concatenate(
            [t[cat[:, i]] for i, t in enumerate(self.teachers)] + [dense],
            axis=-1,
        )
        score = feats @ self.head
        label = (score > 0).astype(np.float32)  # stationary teacher threshold
        return {"cat": cat, "dense": dense, "label": label}

    def __iter__(self) -> Iterator[dict[str, np.ndarray]]:
        i = 0
        while self.num_batches is None or i < self.num_batches:
            yield self.batch(i)
            i += 1


def ctr_record_dtype(dense_features: int, n_cat: int) -> np.dtype:
    """One fixed-size record: label f32 | dense f32 x D | cat i32 x F —
    4-byte little-endian fields so the record length is static and the
    native fixed-record loader can mmap/stride it."""
    return np.dtype([
        ("label", "<f4"),
        ("dense", "<f4", (dense_features,)),
        ("cat", "<i4", (n_cat,)),
    ])


def make_ctr_record_file(path: str, label: np.ndarray, dense: np.ndarray,
                         cat: np.ndarray) -> int:
    """Write [N] label / [N, D] dense / [N, F] cat as a CTR record file
    (test/tooling writer — real datasets convert offline via
    tools/make_ctr_records.py). Returns N."""
    N, D = dense.shape
    F = cat.shape[1]
    arr = np.empty(N, ctr_record_dtype(D, F))
    arr["label"] = np.asarray(label, np.float32)
    arr["dense"] = np.asarray(dense, np.float32)
    arr["cat"] = np.asarray(cat, np.int32)
    arr.tofile(path)
    return N


class CTRRecordDataset:
    """{"cat" i32 [B,F], "dense" f32 [B,D], "label" f32 [B]} batches from
    a CTR record file through the native loader: deterministic epoch
    shuffle (SplitMix64 Fisher-Yates, identical bits native/Python),
    per-host disjoint stride shards, resume via ``index_offset``.
    Out-of-range ids clip to the configured vocab (defensive: the file
    may have been hashed to a larger vocab than the model's)."""

    def __init__(self, path: str, cfg: RecsysConfig,
                 num_batches: int | None = None, index_offset: int = 0,
                 seed: int | None = None):
        import jax

        from ..runtime.loader import RecordFileLoader

        self.cfg = cfg
        self._dt = ctr_record_dtype(cfg.dense_features,
                                    len(cfg.vocab_sizes))
        self._vocab = np.asarray(cfg.vocab_sizes, np.int32)
        self._validate_layout(path)
        self.loader = RecordFileLoader(
            path, self._dt.itemsize,
            local_batch_size(cfg.global_batch_size),
            seed=cfg.seed if seed is None else seed,
            shard=jax.process_index(),
            n_shards=jax.process_count(), start_batch=index_offset,
            num_batches=num_batches, decode=self._decode,
        )

    def _validate_layout(self, path: str) -> None:
        """A record-layout mismatch (model config vs converter output)
        would otherwise train silently on misaligned bytes — labels
        become arbitrary floats and the id clip hides it. Two guards:
        the converter's sidecar (authoritative when present), and the
        file size must be a whole number of records either way."""
        import json
        import os

        meta_path = path + ".meta.json"
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                meta = json.load(f)
            want = (meta.get("dense_features"), len(meta.get(
                "vocab_sizes", [])), meta.get("record_bytes"))
            have = (self.cfg.dense_features, len(self.cfg.vocab_sizes),
                    self._dt.itemsize)
            if want != have:
                raise ValueError(
                    f"{path}: layout mismatch — file has dense/cat/bytes "
                    f"{want} (from {meta_path}) but the model config "
                    f"implies {have}; set --model.dense_features/"
                    f"--model.vocab_sizes to match the converter output")
        size = os.path.getsize(path)
        if size % self._dt.itemsize:
            raise ValueError(
                f"{path}: {size} bytes is not a whole number of "
                f"{self._dt.itemsize}-byte records — wrong "
                f"dense_features/vocab_sizes for this file?")

    def _decode(self, raw: np.ndarray) -> dict[str, np.ndarray]:
        rec = np.ascontiguousarray(raw).reshape(-1).view(self._dt)
        cat = np.minimum(np.maximum(rec["cat"], 0), self._vocab - 1)
        return {
            "cat": np.ascontiguousarray(cat),
            "dense": np.ascontiguousarray(rec["dense"]),
            "label": np.ascontiguousarray(rec["label"]),
        }

    def __iter__(self):
        return iter(self.loader)
