from .pipeline import (  # noqa: F401
    DataConfig,
    ElasticStream,
    NpzDataset,
    Prefetcher,
    SyntheticClassification,
    WorkerShard,
    local_batch_size,
    make_dataset,
)
from .recsys import RecsysConfig, SyntheticCTR  # noqa: F401
from . import tfdata  # noqa: F401  (TF imported lazily inside)
from .text import (  # noqa: F401
    SyntheticLM,
    SyntheticMLM,
    TextDataConfig,
    TokenFileLM,
    make_text_dataset,
)
