from .pipeline import (  # noqa: F401
    DataConfig,
    NpzDataset,
    Prefetcher,
    SyntheticClassification,
    local_batch_size,
    make_dataset,
)
