"""Input pipeline: per-host sharded batch streams + host-side prefetch.

Reference mechanism (SURVEY.md §2a 'Input pipeline'): feed_dict or tf.data
with `Dataset.shard(num_workers, task_index)` so each worker reads a
disjoint slice. TPU-native shape: each *host* produces its
``global_batch / process_count`` slice (deterministically disjoint via
per-host seeding), `Trainer.put_batch` assembles the global sharded array
(jax.make_array_from_process_local_data), and a background thread keeps
batches ready so the device never waits on the host (SURVEY.md §7 ranks
input-pipeline starvation the #1 hard part).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Any, Callable, Iterable, Iterator

import jax
import numpy as np


@dataclasses.dataclass(frozen=True)
class DataConfig:
    # synthetic | npz:<path> | records:<path> | jpeg:<path>
    dataset: str = "synthetic"
    # Explicit eval source (same syntax as `dataset`). Empty = workload
    # default: a held-out slice for synthetic streams, or — for file-backed
    # datasets with no natural held-out split (e.g. ctr:) — the training
    # file itself, in which case the AUC metric is tagged `train_auc`.
    eval_dataset: str = ""
    global_batch_size: int = 128
    image_size: int = 28
    channels: int = 1
    num_classes: int = 10
    seed: int = 0
    flat: bool = False  # emit (N, H*W*C) instead of (N, H, W, C)
    # Train-time host augmentation (data/augment.py): "none" | "crop_flip"
    # (pad-4 random crop + hflip, the CIFAR recipe; the jpeg: path always
    # runs the ImageNet random-resized-crop recipe instead).
    augment: str = "none"


def batch_rng(seed: int, index: int) -> np.random.RandomState:
    """Per-batch, per-host RandomState: deterministic in (seed, index) and
    disjoint across hosts (process_index folded in). The single definition
    of the stream-seeding scheme — every synthetic dataset uses it, so a
    change to host-disjointness lands everywhere at once."""
    s = (seed * 1_000_003 + index) * 97 + jax.process_index()
    return np.random.RandomState(s & 0x7FFFFFFF)


def local_batch_size(global_batch_size: int) -> int:
    n = jax.process_count()
    if global_batch_size % n != 0:
        raise ValueError(
            f"global_batch_size={global_batch_size} not divisible by "
            f"process_count={n}"
        )
    return global_batch_size // n


class SyntheticClassification:
    """Deterministic, learnable synthetic data: a fixed random linear
    teacher labels gaussian inputs, so loss/accuracy curves are meaningful
    (convergence tests, SURVEY.md §4.5) without dataset files. Per-host
    disjoint by folding process_index into the per-batch seed."""

    def __init__(self, cfg: DataConfig, num_batches: int | None = None,
                 index_offset: int = 0):
        """``index_offset`` shifts the batch stream (same teacher, fresh
        inputs) — how an eval split is produced without changing the task."""
        self.cfg = cfg
        self.num_batches = num_batches
        self.index_offset = index_offset
        self.local_bs = local_batch_size(cfg.global_batch_size)
        rng = np.random.RandomState(cfg.seed)
        dim = cfg.image_size * cfg.image_size * cfg.channels
        self.teacher = rng.randn(dim, cfg.num_classes).astype(np.float32)

    def batch(self, index: int) -> dict[str, np.ndarray]:
        index += self.index_offset
        rng = batch_rng(self.cfg.seed, index)
        cfg = self.cfg
        shape = (
            (self.local_bs, cfg.image_size * cfg.image_size * cfg.channels)
            if cfg.flat
            else (self.local_bs, cfg.image_size, cfg.image_size, cfg.channels)
        )
        x = rng.randn(*shape).astype(np.float32)
        flat = x.reshape(self.local_bs, -1)
        label = np.argmax(flat @ self.teacher, axis=-1).astype(np.int32)
        return {"image": x, "label": label}

    def __iter__(self) -> Iterator[dict[str, np.ndarray]]:
        i = 0
        while self.num_batches is None or i < self.num_batches:
            yield self.batch(i)
            i += 1


class NpzDataset:
    """Epoch-shuffled stream over an .npz with arrays ``image``/``label`` —
    the hook for real MNIST/CIFAR files when present on the host.

    ``num_batches`` bounds the stream; ``index_offset`` fast-forwards past
    already-consumed batches (checkpoint resume). For a true held-out eval
    split, point at a separate eval .npz — an offset stream still draws
    from the same examples."""

    def __init__(self, path: str, cfg: DataConfig, shuffle: bool = True,
                 num_batches: int | None = None, index_offset: int = 0):
        data = np.load(path)
        self.images = data["image"]
        self.labels = data["label"]
        self.cfg = cfg
        self.shuffle = shuffle
        self.num_batches = num_batches
        self.index_offset = index_offset
        self.local_bs = local_batch_size(cfg.global_batch_size)

    def _batches_per_epoch(self) -> int:
        n = len(self.images) // jax.process_count()
        return max(n // self.local_bs, 1)

    def batch(self, index: int) -> dict[str, np.ndarray]:
        bpe = self._batches_per_epoch()
        epoch, pos = divmod(index, bpe)
        order = np.arange(len(self.images))
        if self.shuffle:
            # identical shuffle on every host, disjoint strided slices
            np.random.RandomState(self.cfg.seed + epoch).shuffle(order)
        order = order[jax.process_index():: jax.process_count()]
        idx = order[pos * self.local_bs : (pos + 1) * self.local_bs]
        return {"image": self.images[idx], "label": self.labels[idx]}

    def __iter__(self):
        i = 0
        while self.num_batches is None or i < self.num_batches:
            yield self.batch(i + self.index_offset)
            i += 1


def make_dataset(cfg: DataConfig, num_batches: int | None = None,
                 index_offset: int = 0, train: bool = True) -> Iterable:
    """``train=False`` turns off stochastic augmentation (records:) and
    switches the jpeg: path to the deterministic resize+center-crop eval
    decode — the workloads' eval_dataset_fn contract."""
    if cfg.dataset == "synthetic":
        return SyntheticClassification(cfg, num_batches, index_offset)
    if cfg.dataset.startswith("npz:"):
        return NpzDataset(cfg.dataset[4:], cfg, num_batches=num_batches,
                          index_offset=index_offset)
    if cfg.dataset.startswith("records:"):
        from .records import RecordClassificationDataset

        return RecordClassificationDataset(
            cfg.dataset[len("records:"):],
            (cfg.image_size, cfg.image_size, cfg.channels),
            cfg.global_batch_size, seed=cfg.seed,
            num_batches=num_batches, index_offset=index_offset,
            flat=cfg.flat, augment=cfg.augment if train else "none",
        )
    if cfg.dataset.startswith("jpeg:"):
        from .jpeg_records import JpegClassificationDataset

        # train: shuffled epoch order + random-resized-crop/hflip;
        # eval: in-order, resize + center crop. Point eval at a held-out
        # record pair via the config override (--data.dataset=jpeg:...).
        return JpegClassificationDataset(
            cfg.dataset[len("jpeg:"):], cfg.image_size,
            cfg.global_batch_size, seed=cfg.seed, train=train,
            num_batches=num_batches, index_offset=index_offset,
        )
    raise ValueError(f"Unknown dataset '{cfg.dataset}'")


class RetryingIterator:
    """Self-healing batch stream: absorbs transient IOError-class faults
    by RE-SEEKING the stream at the failed index instead of dying.

    Sound because every dataset here is a pure function of
    ``(seed, index)`` (the ``batch_rng`` scheme / ``index_offset``
    contract): rebuilding the source at the index of the failed fetch
    reproduces exactly the batch the consumer was owed, so a recovered
    run is bit-identical to an unfaulted one.

    ``make_source(start_index)`` must return an iterable whose first
    batch is the stream's ``start_index``-th (0-based) — e.g.
    ``lambda i: make_dataset(cfg, index_offset=i)``. Retries per fetch
    are bounded by ``policy`` (resilience/retry.py: exponential backoff,
    seeded jitter, obs counters ``retry_attempts_total{site}`` /
    ``retry_exhausted_total{site}``); a permanent failure surfaces as
    ``RetryExhausted`` with the underlying IOError chained, which the
    train loop's emergency-checkpoint path and the Supervisor's
    transient classification both understand.
    """

    def __init__(self, make_source: Callable[[int], Iterable], policy=None,
                 *, start_index: int = 0, site: str = "data", registry=None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        # lazy import: keeps data/ importable without the resilience
        # package being fully initialized (it imports train/, which some
        # tools load after data/)
        from ..resilience import retry as retry_lib

        self._retry = retry_lib
        self.policy = policy if policy is not None else retry_lib.RetryPolicy()
        self.make_source = make_source
        self.site = site
        self.registry = registry
        self.clock = clock
        self.sleep = sleep
        #: batches successfully delivered so far (== next index to fetch)
        self.index = start_index
        self._it = iter(make_source(start_index))

    def __iter__(self) -> "RetryingIterator":
        return self

    def _reseek(self, failures: int, exc: BaseException) -> None:
        self._it = iter(self.make_source(self.index))

    def __next__(self):
        batch = self._retry.retry_call(
            lambda: next(self._it),
            policy=self.policy, site=self.site, registry=self.registry,
            clock=self.clock, sleep=self.sleep, on_retry=self._reseek,
        )
        self.index += 1
        return batch


def quarantined_raw_start(start_step: int, quarantine) -> int:
    """Raw batch index already consumed once ``start_step`` *effective*
    (non-quarantined) batches have been delivered. With holes in the
    stream, effective step numbering and raw ``(seed, index)`` numbering
    diverge — this is the single translation both the filter below and
    the blame machinery (resilience/anomaly.py) use, so they can never
    disagree about which raw batch feeds which step."""
    raw = int(start_step)
    for q in sorted({int(i) for i in quarantine}):
        if q <= raw:
            raw += 1
    return raw


class QuarantineFilter:
    """Batch stream with quarantined raw indices REMOVED: the numeric-
    anomaly defense's data half (docs/resilience.md "Numeric anomalies").

    ``make_source(raw_index)`` follows the RetryingIterator contract —
    it returns an iterable whose first batch is raw index
    ``raw_index + 1`` (batches are 1-based; batch i normally feeds step
    i). Quarantined indices are skipped by *re-seeking the source
    around them* — the bad batch is never even fetched, so a record
    whose very decode raises (or re-poisons) cannot re-injure a
    recovered run. Because every dataset here is a pure function of
    ``(seed, index)``, the surviving stream — hence the training
    trajectory — is a pure function of ``(seed, quarantine set)``:
    same-seed recovery stays bit-identical, with the holes applied
    identically on every incarnation.

    ``start_step`` counts EFFECTIVE batches already consumed (a resumed
    run's restored step); the raw seek position is derived via
    ``quarantined_raw_start``. ``raw`` is the raw index of the most
    recently delivered batch — resilience/anomaly.AnomalyPolicy reads
    it (``index_fn=lambda: stream.raw``) to blame the exact
    ``(seed, index)`` a non-finite step consumed, so do not interpose a
    Prefetcher between this filter and the policy (prefetch runs the
    cursor ahead of the step being blamed)."""

    def __init__(self, make_source: Callable[[int], Iterable],
                 quarantine: Iterable[int] = (), *, start_step: int = 0,
                 registry=None):
        self.make_source = make_source
        self.quarantine = frozenset(int(i) for i in quarantine)
        #: raw index of the last delivered batch
        self.raw = quarantined_raw_start(start_step, self.quarantine)
        self._it = iter(make_source(self.raw))
        if registry is None:
            from ..obs.registry import default_registry

            registry = default_registry()
        self._m_skipped = registry.counter(
            "anomaly_skipped_batches_total",
            "batches dropped by the numeric-anomaly defense",
            cause="quarantined",
        )

    def __iter__(self) -> "QuarantineFilter":
        return self

    def __next__(self):
        nxt = self.raw + 1
        if nxt in self.quarantine:
            while nxt in self.quarantine:
                self._m_skipped.inc()
                nxt += 1
            # re-seek AROUND the hole: rebuild the source just past it
            # instead of fetching-and-discarding the condemned batch
            self._it = iter(self.make_source(nxt - 1))
            self.raw = nxt - 1
        batch = next(self._it)
        self.raw += 1
        return batch


@dataclasses.dataclass(frozen=True)
class WorkerShard:
    """Which disjoint slice of every GLOBAL batch one fleet worker loads
    — the data half of the elastic fleet (docs/resilience.md "Elastic
    fleet"). The global batch at index i is a pure function of
    ``(seed, i)`` and never depends on the worker count; a shard is just
    a strided view ``[rank::world]`` of it, so the union over ranks is
    always exactly the global batch and a resize changes who loads what,
    never what the gang trains on."""

    rank: int
    world: int

    def __post_init__(self):
        if self.world < 1:
            raise ValueError(f"WorkerShard.world must be >= 1, got "
                             f"{self.world}")
        if not 0 <= self.rank < self.world:
            raise ValueError(
                f"WorkerShard.rank must be in [0, {self.world}), got "
                f"{self.rank}")

    def slice(self, batch):
        """Strided ``[rank::world]`` view of every array in ``batch`` —
        disjoint across ranks, union == the global batch, well-defined
        for batch sizes not divisible by ``world`` (slice lengths differ
        by at most 1)."""
        if isinstance(batch, dict):
            return {k: v[self.rank::self.world] for k, v in batch.items()}
        return batch[self.rank::self.world]


class ElasticStream:
    """Reshardable worker view over a global ``(seed, index)``-pure batch
    stream — the live-rewrite seam the fleet's elastic resize drives
    (resilience/fleet.ElasticWorker ``on_reshard``).

    ``make_source(i0)`` follows the QuarantineFilter contract: it returns
    an iterable whose first batch is GLOBAL index ``i0 + 1`` (batch i
    feeds step i). The stream holds a current ``WorkerShard`` and yields
    ``shard.slice(global_batch)`` — or the whole batch when ``shard`` is
    None (the collective-free test rig's replica mode, where every
    worker computes the full-batch update in place of an allreduce).

    ``reshard(shard, at_index)`` schedules a shard switch: batches with
    index > ``at_index`` (the fleet barrier step) use the new shard; an
    ``at_index`` already behind the cursor applies immediately. Because
    the global stream is pure in ``(seed, index)`` and switches bind to
    indices, the delivered slices are a pure function of
    ``(seed, resize schedule)``: a live rewrite is bit-identical to a
    fresh stream built with the same schedule.

    Single-threaded by contract: ``reshard`` is called from the same
    loop that consumes the stream (train/callbacks.ElasticCallback runs
    on the step seam) — do not interpose a Prefetcher, which would run
    the cursor ahead of the barrier being applied (same rule as the
    anomaly defense's blame cursor)."""

    def __init__(self, make_source: Callable[[int], Iterable],
                 shard: WorkerShard | None = None, *, start_index: int = 0):
        self.make_source = make_source
        self.shard = shard
        #: global index of the most recently delivered batch
        self.index = int(start_index)
        self._it = iter(make_source(self.index))
        #: scheduled switches, ascending by at_index
        self._pending: list[tuple[int, WorkerShard | None]] = []
        #: applied (at_index, rank, world) history — the realized resize
        #: schedule, the determinism oracle's replay input
        self.schedule: list[tuple[int, int | None, int | None]] = []

    def reshard(self, shard: WorkerShard | None, at_index: int) -> None:
        """Switch to ``shard`` for batches with index > ``at_index``."""
        at = int(at_index)
        if at <= self.index:
            self._apply(at, shard)
            return
        # a newer plan for the same (or an earlier) switch point
        # supersedes anything scheduled at or after it
        self._pending = [(a, s) for a, s in self._pending if a < at]
        self._pending.append((at, shard))

    def _apply(self, at: int, shard: WorkerShard | None) -> None:
        self.shard = shard
        self.schedule.append(
            (at, shard.rank if shard else None,
             shard.world if shard else None))

    def __iter__(self) -> "ElasticStream":
        return self

    def __next__(self):
        nxt = self.index + 1
        while self._pending and self._pending[0][0] < nxt:
            self._apply(*self._pending.pop(0))
        batch = next(self._it)
        self.index = nxt
        return self.shard.slice(batch) if self.shard is not None else batch


class Prefetcher:
    """Background-thread prefetch: keeps up to ``depth`` host batches ready.
    The Python tier of the input pipeline; the native (C++) loader in
    runtime/ plugs in beneath it for decode-heavy workloads."""

    _DONE = object()
    # Bound at class-definition time: the generator's `finally` can run
    # during interpreter shutdown, when the module-global `queue` name may
    # already be torn down (observed as a TypeError in except-clause).
    _Empty = queue.Empty

    def __init__(self, source: Iterable, depth: int = 2,
                 transform: Callable[[Any], Any] | None = None):
        self.source = source
        self.depth = depth
        self.transform = transform

    def __iter__(self):
        q: queue.Queue = queue.Queue(maxsize=self.depth)
        stop = threading.Event()
        error: list[BaseException] = []

        def worker():
            try:
                for item in self.source:
                    if stop.is_set():
                        return
                    if self.transform is not None:
                        item = self.transform(item)
                    q.put(item)
            except BaseException as e:  # surface in consumer thread
                error.append(e)
            finally:
                q.put(self._DONE)

        t = threading.Thread(target=worker, daemon=True, name="prefetcher")
        t.start()
        try:
            while True:
                item = q.get()
                if item is self._DONE:
                    if error:
                        raise error[0]
                    return
                yield item
        finally:
            stop.set()
            # drain so the worker's blocked put() can observe stop
            try:
                while True:
                    q.get_nowait()
            except self._Empty:
                pass
