"""Host-side image augmentation for the input pipeline (SURVEY.md §7 M7).

The reference's input path ran per-worker tf.data with decode + random
crop/flip before feeding (SURVEY.md §2a 'Input pipeline'). Augmentation
stays on the HOST here by design: TPU steps are lockstep SPMD programs and
per-image branching (crop offsets, flips) belongs on the CPU where it
overlaps with device compute via the Prefetcher; the device sees only
dense, statically-shaped batches.

All randomness flows through a caller-provided ``np.random.RandomState``
seeded per (seed, batch_index) — the pipeline's resume contract: restoring
at step N reproduces exactly the augmented batches N, N+1, ... that the
uninterrupted run saw.
"""

from __future__ import annotations

import numpy as np


def random_crop_flip(
    images: np.ndarray, rng: np.random.RandomState, *, padding: int = 4
) -> np.ndarray:
    """CIFAR-style train augmentation: zero-pad by ``padding``, take a
    random H×W crop per image, then horizontally flip half of them.
    Vectorized over the batch (one gather + one masked flip)."""
    b, h, w, c = images.shape
    padded = np.pad(
        images, ((0, 0), (padding, padding), (padding, padding), (0, 0))
    )
    ys = rng.randint(0, 2 * padding + 1, b)
    xs = rng.randint(0, 2 * padding + 1, b)
    windows = np.lib.stride_tricks.sliding_window_view(
        padded, (h, w), axis=(1, 2)
    )  # [B, 2p+1, 2p+1, C, H, W]
    out = windows[np.arange(b), ys, xs]  # [B, C, H, W]
    out = np.ascontiguousarray(np.moveaxis(out, 1, -1))  # [B, H, W, C]
    flips = rng.rand(b) < 0.5
    out[flips] = out[flips, :, ::-1]
    return out


def sample_crop_rect(
    h: int, w: int, rng: np.random.RandomState,
    *, scale: tuple[float, float] = (0.08, 1.0),
    ratio: tuple[float, float] = (3 / 4, 4 / 3), attempts: int = 10,
) -> tuple[int, int, int, int]:
    """Sample the Inception-recipe area/aspect crop rect (y, x, ch, cw)
    for an H×W image; center-square fallback when no sample fits. The
    ONE definition of the crop policy — shared by the PIL path
    (:func:`random_resized_crop`) and the native libjpeg decoder
    (data/native_jpeg.py), so the two decoders draw identical rects from
    identical rng states."""
    area = h * w
    for _ in range(attempts):
        target_area = area * rng.uniform(*scale)
        log_ratio = np.log(ratio)
        aspect = np.exp(rng.uniform(*log_ratio))
        cw = int(round(np.sqrt(target_area * aspect)))
        ch = int(round(np.sqrt(target_area / aspect)))
        if 0 < cw <= w and 0 < ch <= h:
            y = rng.randint(0, h - ch + 1)
            x = rng.randint(0, w - cw + 1)
            return y, x, ch, cw
    side = min(h, w)
    return max(0, (h - side) // 2), max(0, (w - side) // 2), side, side


def random_resized_crop(
    image: np.ndarray, rng: np.random.RandomState, out_size: int,
    *, scale: tuple[float, float] = (0.08, 1.0),
    ratio: tuple[float, float] = (3 / 4, 4 / 3), attempts: int = 10,
) -> np.ndarray:
    """ImageNet-style train augmentation for ONE [H, W, C] uint8 image:
    sample an area/aspect crop (Inception recipe), resize to
    out_size×out_size (PIL bilinear)."""
    from PIL import Image

    h, w = image.shape[:2]
    y, x, ch, cw = sample_crop_rect(
        h, w, rng, scale=scale, ratio=ratio, attempts=attempts)
    crop = image[y:y + ch, x:x + cw]
    pil = Image.fromarray(crop)
    pil = pil.resize((out_size, out_size), Image.BILINEAR)
    return np.asarray(pil)


def center_crop(image: np.ndarray, size: int) -> np.ndarray:
    """Eval-side deterministic crop of ONE [H, W, C] image."""
    h, w = image.shape[:2]
    y = max(0, (h - size) // 2)
    x = max(0, (w - size) // 2)
    return image[y:y + size, x:x + size]


def resize_center_crop(
    image: np.ndarray, out_size: int, *, resize_frac: float = 0.875
) -> np.ndarray:
    """Eval ImageNet recipe: resize short side to out_size/resize_frac,
    then center-crop out_size×out_size."""
    from PIL import Image

    h, w = image.shape[:2]
    short = int(round(out_size / resize_frac))
    if h < w:
        nh, nw = short, max(short, int(round(w * short / h)))
    else:
        nh, nw = max(short, int(round(h * short / w))), short
    pil = Image.fromarray(image).resize((nw, nh), Image.BILINEAR)
    return center_crop(np.asarray(pil), out_size)


def hflip(image: np.ndarray, rng: np.random.RandomState) -> np.ndarray:
    return image[:, ::-1] if rng.rand() < 0.5 else image
