"""Native libjpeg decode stage (ctypes over native/dtf_jpeg.cpp).

The JPEG input path's hot loop — header parse, DCT-domain downscaled
decode, crop, bilinear resize — in C++ with a thread pool, plugged under
``JpegClassificationDataset`` (``decoder="native"``). The crop POLICY
(which rect, which flips) stays in Python (augment.sample_crop_rect), so
the augmentation recipe has exactly one definition; this stage only
executes pixels. Closes the round-2 'two separate input stacks' gap
(VERDICT r2 Weak #7): the native tier now serves the flagship JPEG path,
not just the dense-record loader.

Build policy mirrors runtime/native.py: compile on first use (g++ -O3,
links -ljpeg), cache the .so beside the source, degrade silently to the
PIL path when the toolchain or libjpeg is missing.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

import numpy as np

logger = logging.getLogger(__name__)

_REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO, "native", "dtf_jpeg.cpp")
_BUILD_DIR = os.path.join(_REPO, "native", "build")
_SO = os.path.join(_BUILD_DIR, "libdtf_jpeg.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


def _configure(lib: ctypes.CDLL) -> ctypes.CDLL:
    c = ctypes
    lib.dtf_jpeg_dims.restype = c.c_int
    lib.dtf_jpeg_dims.argtypes = [
        c.POINTER(c.c_uint8), c.POINTER(c.c_int64), c.POINTER(c.c_int64),
        c.c_int64, c.POINTER(c.c_int64),
    ]
    lib.dtf_jpeg_decode_crop_resize.restype = c.c_int
    lib.dtf_jpeg_decode_crop_resize.argtypes = [
        c.POINTER(c.c_uint8), c.POINTER(c.c_int64), c.POINTER(c.c_int64),
        c.POINTER(c.c_int64), c.c_int64, c.c_int,
        c.POINTER(c.c_uint8), c.c_int,
    ]
    return lib


def load_library() -> ctypes.CDLL | None:
    """Build (once) and load libdtf_jpeg.so; None when unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            if not os.path.exists(_SO) or (
                os.path.getmtime(_SRC) > os.path.getmtime(_SO)
            ):
                os.makedirs(_BUILD_DIR, exist_ok=True)
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                     _SRC, "-o", _SO, "-ljpeg", "-pthread"],
                    check=True, capture_output=True, text=True,
                )
            _lib = _configure(ctypes.CDLL(_SO))
        except (OSError, subprocess.CalledProcessError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            logger.info("native jpeg decoder unavailable (%s); "
                        "using the PIL path", detail.strip()[:200])
            _lib = None
        return _lib


def available() -> bool:
    return load_library() is not None


def _i64p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _u8p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _bounded(data: np.ndarray, offsets: np.ndarray,
             lengths: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Clamp (offset, length) pairs to the data buffer: a corrupt index
    entry must become a catchable short-stream decode failure (zero-fill
    contract), never an out-of-bounds read in C."""
    off = np.clip(np.ascontiguousarray(offsets, np.int64), 0, data.size)
    ln = np.clip(np.ascontiguousarray(lengths, np.int64), 0,
                 data.size - off)
    return off, ln


def jpeg_dims(data: np.ndarray, offsets: np.ndarray,
              lengths: np.ndarray) -> np.ndarray:
    """[N, 2] (h, w) per stream; zeros for unparsable streams."""
    lib = load_library()
    n = len(offsets)
    dims = np.zeros((n, 2), np.int64)
    off, ln = _bounded(data, offsets, lengths)
    lib.dtf_jpeg_dims(_u8p(data), _i64p(off), _i64p(ln), n, _i64p(dims))
    return dims


def decode_crop_resize(data: np.ndarray, offsets: np.ndarray,
                       lengths: np.ndarray, rects: np.ndarray,
                       out_size: int, n_threads: int) -> np.ndarray:
    """Decode N streams, crop rect (y, x, ch, cw in full-res coords),
    bilinear-resize to [N, out_size, out_size, 3] u8. Failed streams come
    back zeroed (the caller's record file is validated at conversion
    time; a zero image in a training batch is noise, not a crash)."""
    lib = load_library()
    n = len(offsets)
    out = np.empty((n, out_size, out_size, 3), np.uint8)
    off, ln = _bounded(data, offsets, lengths)
    rc = np.ascontiguousarray(rects, np.int64)
    bad = lib.dtf_jpeg_decode_crop_resize(
        _u8p(data), _i64p(off), _i64p(ln), _i64p(rc), n, out_size,
        _u8p(out), n_threads,
    )
    if bad:
        logger.warning("native jpeg decode: %d/%d streams failed "
                       "(zero-filled)", bad, n)
    return out
