"""distributed_tensorflow_tpu — a TPU-native distributed training framework.

A ground-up rebuild of the capability surface of the
``gctian/distributed-tensorflow`` parameter-server/worker harness (see
SURVEY.md for the structural analysis), designed TPU-first: one SPMD program
over a named device mesh, XLA collectives on ICI/DCN in place of the PS/gRPC
data plane, a jit-compiled train step in place of the SyncReplicasOptimizer
accumulator/token protocol, and a host-side callback loop with async
multi-host checkpointing in place of MonitoredTrainingSession and its hooks.
"""

__version__ = "0.1.0"

# Chip-session lease guard FIRST, before any submodule can touch a jax
# backend: while tools/chip_session.sh holds the lock, every other
# importer of this package pins itself to CPU (utils/chip_lock.py).
from .utils.chip_lock import pin_cpu_if_locked as _pin_cpu_if_locked

_pin_cpu_if_locked()

from . import data  # noqa: F401
from . import models  # noqa: F401
from . import obs  # noqa: F401
from . import parallel  # noqa: F401
from . import resilience  # noqa: F401
from . import serve  # noqa: F401
from . import train  # noqa: F401
from . import utils  # noqa: F401
from . import workloads  # noqa: F401
