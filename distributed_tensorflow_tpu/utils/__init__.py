from . import multihost  # noqa: F401
