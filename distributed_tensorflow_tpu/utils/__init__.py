from . import compat  # noqa: F401
from . import config  # noqa: F401
from . import flops  # noqa: F401
from . import multihost  # noqa: F401
