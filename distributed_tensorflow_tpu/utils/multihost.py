"""Cross-host divergence detection — the distributed analog of a race
detector (SURVEY.md §5.2).

The SPMD model eliminates parameter data races by construction (the
reference's race surface was async PS updates, documented as 'stale
gradients' at $TF sync_replicas_optimizer.py:48-55, plus Coordinator thread
lifecycle). What can still go wrong on TPU is *cross-host divergence*: hosts
disagreeing on step count, RNG keys, compiled program, or data order —
which deadlocks or silently corrupts collectives. Debug-mode asserts here
catch it early; enable via ``DebugConfig.check_divergence`` or the
``DTF_TPU_CHECK_DIVERGENCE`` env var.
"""

from __future__ import annotations

import hashlib
import os
from typing import Any

import jax
import numpy as np


def divergence_checks_enabled() -> bool:
    return os.environ.get("DTF_TPU_CHECK_DIVERGENCE", "0") not in ("0", "", "false")


def _fingerprint(tree: Any) -> np.ndarray:
    """Stable 64-bit host-side fingerprint of a small pytree."""
    leaves = jax.tree.leaves(tree)
    h = hashlib.blake2b(digest_size=8)
    for leaf in leaves:
        h.update(np.asarray(leaf).tobytes())
    return np.frombuffer(h.digest(), dtype=np.int64)


def assert_same_across_hosts(tree: Any, name: str = "value") -> None:
    """Raise if any host disagrees on ``tree`` (step counters, RNG keys,
    loss scalars — cheap things, not parameters). No-op single-process.

    The reference's closest mechanism was nothing at harness level; TF's
    modern substrate grew coordination-service health checks. This is the
    SPMD-native version: fingerprint + process_allgather + compare.
    """
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    fp = _fingerprint(tree)
    all_fps = multihost_utils.process_allgather(fp)
    if not np.all(all_fps == all_fps[0]):
        raise AssertionError(
            f"Cross-host divergence on '{name}': fingerprints "
            f"{all_fps.ravel().tolist()} differ across processes"
        )


def broadcast_from_chief(tree: Any) -> Any:
    """Make every host adopt process 0's value (config resolution, run ids).
    No-op single-process."""
    if jax.process_count() == 1:
        return tree
    from jax.experimental import multihost_utils

    return multihost_utils.broadcast_one_to_all(tree)
