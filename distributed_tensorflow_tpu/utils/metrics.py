"""Streaming evaluation metrics as mergeable sufficient statistics.

The eval contract in this framework (models/common.classification_eval_fn)
is that an eval step returns SUMMED statistics, so shards and batches
aggregate exactly by addition — the TPU-native form of the reference
substrate's streaming metrics, which accumulate confusion-matrix local
variables per threshold bucket ($TF/python/ops/metrics_impl.py:809
``tf.metrics.auc``: true/false positives/negatives at `num_thresholds`
buckets, finalized by trapezoidal summation).

Here the sufficient statistic for AUC is a pair of fixed-size score
histograms (positives, negatives) — fixed shapes, one scatter-add per
batch, XLA-friendly — and the finalizer computes the exact rank-sum
(Mann–Whitney) AUC of the bucketized scores, with half credit for ties
inside a bucket. With B buckets the bucketization error is O(1/B);
B=512 matches the substrate's default granularity (num_thresholds=200)
with margin.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["auc_histograms", "auc_from_histograms", "AUC_BINS"]

AUC_BINS = 512


def auc_histograms(logits, labels, bins: int = AUC_BINS):
    """Per-batch AUC sufficient statistics (device-side, fixed shape).

    logits: [N] pre-sigmoid scores; labels: [N] {0,1}.
    Returns {"auc_pos_hist": [bins], "auc_neg_hist": [bins]} — summable
    across batches and eval shards.
    """
    p = jax.nn.sigmoid(jnp.asarray(logits, jnp.float32))
    idx = jnp.clip((p * bins).astype(jnp.int32), 0, bins - 1)
    pos = jnp.asarray(labels, jnp.float32)
    pos_hist = jnp.zeros((bins,), jnp.float32).at[idx].add(pos)
    neg_hist = jnp.zeros((bins,), jnp.float32).at[idx].add(1.0 - pos)
    return {"auc_pos_hist": pos_hist, "auc_neg_hist": neg_hist}


def auc_from_histograms(pos_hist, neg_hist) -> float:
    """Finalize: exact rank-sum AUC of the bucketized scores.

    AUC = P(score_pos > score_neg) + 0.5 · P(tie), estimated over all
    pos×neg pairs: for each bucket b, its positives beat every negative
    in buckets < b and tie (half credit) with negatives in bucket b.
    Returns NaN when either class is empty (undefined, like the
    substrate's 0/0 guard).
    """
    pos = np.asarray(pos_hist, np.float64)
    neg = np.asarray(neg_hist, np.float64)
    P, N = pos.sum(), neg.sum()
    if P == 0 or N == 0:
        return float("nan")
    neg_below = np.cumsum(neg) - neg  # negatives strictly below bucket b
    wins = float((pos * (neg_below + 0.5 * neg)).sum())
    return float(wins / (P * N))
