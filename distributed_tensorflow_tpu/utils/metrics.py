"""Streaming evaluation metrics as mergeable sufficient statistics.

The eval contract in this framework (models/common.classification_eval_fn)
is that an eval step returns SUMMED statistics, so shards and batches
aggregate exactly by addition — the TPU-native form of the reference
substrate's streaming metrics, which accumulate confusion-matrix local
variables per threshold bucket ($TF/python/ops/metrics_impl.py:809
``tf.metrics.auc``: true/false positives/negatives at `num_thresholds`
buckets, finalized by trapezoidal summation).

Here the sufficient statistic for AUC is a pair of fixed-size score
histograms (positives, negatives) — fixed shapes, one scatter-add per
batch, XLA-friendly — and the finalizer computes the exact rank-sum
(Mann–Whitney) AUC of the bucketized scores, with half credit for ties
inside a bucket.

AUC is rank-based and sigmoid is monotone, so scores are bucketized in
LOGIT space (uniform over [-LOGIT_RANGE, LOGIT_RANGE]), not probability
space: a probability-space grid would collapse every confidently-scored
example into the two end buckets (sigmoid(7.5) and sigmoid(9) differ by
4e-4 — same bucket out of 512 — despite clean separability). In logit
space the tie window is 2·LOGIT_RANGE/B ≈ 0.06 logits per bucket; only
pairs whose logits BOTH saturate beyond ±LOGIT_RANGE (where sigmoid is
flat to <3e-7) still tie. B=512 exceeds the substrate's default
granularity (num_thresholds=200).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

__all__ = ["auc_histograms", "auc_from_histograms", "AUC_BINS",
           "LOGIT_RANGE"]

AUC_BINS = 512
LOGIT_RANGE = 15.0  # sigmoid is flat to <3e-7 beyond this


def auc_histograms(logits, labels, bins: int = AUC_BINS):
    """Per-batch AUC sufficient statistics (device-side, fixed shape).

    logits: [N] pre-sigmoid scores; labels: [N] {0,1}.
    Returns {"auc_pos_hist": [bins], "auc_neg_hist": [bins]} — summable
    across batches and eval shards. Bucketized uniformly in logit space
    (module docstring: rank-equivalent to sigmoid scores, no saturation
    collapse).
    """
    x = jnp.asarray(logits, jnp.float32)
    p = (x + LOGIT_RANGE) / (2.0 * LOGIT_RANGE)
    idx = jnp.clip((p * bins).astype(jnp.int32), 0, bins - 1)
    pos = jnp.asarray(labels, jnp.float32)
    pos_hist = jnp.zeros((bins,), jnp.float32).at[idx].add(pos)
    neg_hist = jnp.zeros((bins,), jnp.float32).at[idx].add(1.0 - pos)
    return {"auc_pos_hist": pos_hist, "auc_neg_hist": neg_hist}


def auc_from_histograms(pos_hist, neg_hist) -> float:
    """Finalize: exact rank-sum AUC of the bucketized scores.

    AUC = P(score_pos > score_neg) + 0.5 · P(tie), estimated over all
    pos×neg pairs: for each bucket b, its positives beat every negative
    in buckets < b and tie (half credit) with negatives in bucket b.
    Returns NaN when either class is empty (undefined, like the
    substrate's 0/0 guard).
    """
    pos = np.asarray(pos_hist, np.float64)
    neg = np.asarray(neg_hist, np.float64)
    P, N = pos.sum(), neg.sum()
    if P == 0 or N == 0:
        return float("nan")
    neg_below = np.cumsum(neg) - neg  # negatives strictly below bucket b
    wins = float((pos * (neg_below + 0.5 * neg)).sum())
    return float(wins / (P * N))
