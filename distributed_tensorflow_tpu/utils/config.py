"""Config system: one dataclass tree + flat dotted-key CLI overrides.

Replaces the reference's per-script flag layer (SURVEY.md §5.6: tf.app.flags
``--ps_hosts/--worker_hosts/--job_name/--task_index/--sync_replicas/...`` +
the TF_CONFIG env var). Topology flags become the mesh section (axis sizes,
not host:port lists); every run serializes its resolved config into the
checkpoint directory for reproducibility.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Sequence, Type, TypeVar, get_args, get_origin

T = TypeVar("T")


def to_dict(cfg: Any) -> Any:
    if dataclasses.is_dataclass(cfg):
        return {
            f.name: to_dict(getattr(cfg, f.name)) for f in dataclasses.fields(cfg)
        }
    if isinstance(cfg, (list, tuple)):
        return [to_dict(v) for v in cfg]
    if isinstance(cfg, dict):
        return {k: to_dict(v) for k, v in cfg.items()}
    return cfg


def to_json(cfg: Any, **kwargs) -> str:
    return json.dumps(to_dict(cfg), indent=2, sort_keys=True, **kwargs)


def from_dict(cls: Type[T], d: Any) -> T:
    """Rebuild a dataclass tree from a plain dict (checkpoint restore)."""
    if not dataclasses.is_dataclass(cls):
        return d
    kwargs = {}
    fields = {f.name: f for f in dataclasses.fields(cls)}
    for k, v in d.items():
        if k not in fields:
            raise ValueError(f"Unknown config field '{k}' for {cls.__name__}")
        ftype = fields[k].type
        ftype = _resolve_type(ftype, cls)
        if dataclasses.is_dataclass(ftype) and isinstance(v, dict):
            kwargs[k] = from_dict(ftype, v)
        elif (get_origin(ftype) is tuple or ftype is tuple) and isinstance(v, list):
            kwargs[k] = tuple(v)
        else:
            kwargs[k] = v
    return cls(**kwargs)


def _resolve_type(ftype, owner_cls):
    if isinstance(ftype, str):
        import builtins
        import sys
        import typing

        mod = sys.modules.get(owner_cls.__module__)
        ns = {**vars(builtins), **vars(typing)}
        if mod is not None:
            ns.update(vars(mod))
        ftype = eval(ftype, ns)  # annotations from our own dataclasses
    # unwrap Optional[X]
    args = [a for a in get_args(ftype) if a is not type(None)]
    if get_origin(ftype) is not None and len(args) == 1 and get_origin(ftype) not in (tuple, list, dict):
        return args[0]
    return ftype


def _parse_value(raw: str, ftype) -> Any:
    # callers pass an already-resolved ftype (see _replace_path)
    if ftype is bool or (isinstance(ftype, type) and issubclass(ftype, bool)):
        if raw.lower() in ("1", "true", "yes"):
            return True
        if raw.lower() in ("0", "false", "no"):
            return False
        raise ValueError(f"Not a bool: {raw!r}")
    if raw.lower() == "none":
        return None  # before numeric parse, so Optional[int]=none works
    try:
        if isinstance(ftype, type) and issubclass(ftype, int) and not issubclass(ftype, bool):
            return int(raw)
        if isinstance(ftype, type) and issubclass(ftype, float):
            return float(raw)
    except TypeError:
        pass
    # tuples / lists / anything json-ish
    is_tuple = get_origin(ftype) is tuple or ftype is tuple
    # raw[:1] must be non-empty before the membership test: "" is a
    # substring of every string, so a bare `--key=` (empty value, e.g.
    # --checkpoint.directory= to disable) would wrongly take the strict
    # JSON branch and crash instead of falling through to a raw string
    if (is_tuple or get_origin(ftype) is list or ftype is list
            or (raw[:1] and raw[:1] in "[({")):
        val = json.loads(raw)
        return tuple(val) if is_tuple else val
    # fall back on literal parse, then raw string
    try:
        return json.loads(raw)
    except (ValueError, TypeError):
        return raw


def apply_overrides(cfg: T, overrides: Sequence[str]) -> T:
    """``apply_overrides(cfg, ["train.lr=0.1", "mesh.model=4"])``.

    The TPU-native stand-in for the reference's flag parsing: one flat
    namespace over the whole tree, type-checked against the dataclass
    field, first path component selects the section.
    """
    for item in overrides:
        if "=" not in item:
            raise ValueError(f"Override must be key=value, got {item!r}")
        key, raw = item.split("=", 1)
        key = key.lstrip("-")
        path = key.split(".")
        cfg = _replace_path(cfg, path, raw, key)
    return cfg


def _replace_path(node: Any, path: list[str], raw: str, full_key: str):
    name, rest = path[0], path[1:]
    if not dataclasses.is_dataclass(node):
        raise ValueError(f"Cannot descend into non-config at '{full_key}'")
    fields = {f.name: f for f in dataclasses.fields(node)}
    if name not in fields:
        valid = ", ".join(sorted(fields))
        raise ValueError(f"Unknown config key '{full_key}' (at '{name}'; valid: {valid})")
    if rest:
        child = _replace_path(getattr(node, name), rest, raw, full_key)
        return dataclasses.replace(node, **{name: child})
    ftype = _resolve_type(fields[name].type, type(node))
    value = _parse_value(raw, ftype)
    return dataclasses.replace(node, **{name: value})


def parse_argv(cfg: T, argv: Sequence[str]) -> T:
    """Parse ``--a.b=c``-style argv into config overrides."""
    return apply_overrides(cfg, [a for a in argv if a.startswith("--") and "=" in a])
