"""Cross-version jax import shims — ONE home for moved/deprecated aliases.

jax has moved ``shard_map`` twice: 0.4.x exposes it only at
``jax.experimental.shard_map.shard_map``; newer releases promote it to
``jax.shard_map`` (and eventually drop the experimental path). The
replication-check kwarg was renamed too (``check_rep`` → ``check_vma``).
Every module and test in this repo imports ``shard_map`` from here so a
jax upgrade is a one-line change instead of a grep-and-pray sweep — the
same reason the reference harness funneled its ``tf.compat`` touches
through one module.
"""

from __future__ import annotations

import functools
import inspect

try:  # jax >= 0.5.3: promoted to the top-level namespace
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]
except ImportError:  # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map

_PARAMS = frozenset(inspect.signature(_shard_map).parameters)


@functools.wraps(_shard_map)
def shard_map(*args, **kwargs):
    """``shard_map`` with the replication-check kwarg spelled either way:
    callers may pass ``check_vma`` (new) or ``check_rep`` (old) and the
    one the installed jax understands is forwarded."""
    if "check_vma" in kwargs and "check_vma" not in _PARAMS:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    elif "check_rep" in kwargs and "check_rep" not in _PARAMS:
        kwargs["check_vma"] = kwargs.pop("check_rep")
    return _shard_map(*args, **kwargs)


try:  # jax >= 0.6: first-class axis-size query
    from jax.lax import axis_size  # type: ignore[attr-defined]
except ImportError:  # jax 0.4.x idiom
    def axis_size(axis_name):
        """Size of a named mapped axis. ``psum`` of the literal ``1`` is
        constant-folded to the axis size at trace time — the historical
        spelling before ``lax.axis_size`` existed."""
        import jax

        return jax.lax.psum(1, axis_name)


def cost_analysis_dict(compiled) -> dict:
    """``Compiled.cost_analysis()`` as a flat dict across jax versions:
    0.4.x returns a one-element LIST of per-device dicts, newer jax the
    dict itself. Returns {} when the backend offers no analysis."""
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca) if ca else {}


__all__ = ["axis_size", "cost_analysis_dict", "shard_map"]
