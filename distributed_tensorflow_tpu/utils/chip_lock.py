"""Chip-session lock: make the single-device-lease protocol mechanical.

The tunneled TPU exposes exactly one device lease; a second process
initializing the accelerator platform mid-benchmark contends for it and
silently downgrades (or wedges) the measurement session — this cost
round 3 its entire BERT/GPT suite (PERF_NOTES.md "operator error").
The protocol used to be a comment in a shell script; this module makes
it a mechanism:

- ``tools/chip_session.sh CMD...`` takes an exclusive flock, records its
  pid in the lock file, exports ``DTF_CHIP_SESSION=1`` to the command's
  whole process tree, and removes the lock on exit (any exit).
- :func:`pin_cpu_if_locked` — called at package import and by the bench
  harness — detects a *live* lock held by another process tree and pins
  the current process to the CPU backend before any device is touched.
  The session's own children are exempt via the env var; a stale lock
  (holder pid dead) is ignored and cleaned up.

Scope: any Python process that imports ``distributed_tensorflow_tpu``
(or runs pytest, whose conftest pins CPU unconditionally) cannot steal
the lease while a session runs. A bare ``import jax`` that never touches
this package has no automatic in-repo hook (cwd ``sitecustomize`` is not
imported by CPython's site init, and the one sitecustomize slot is the
environment-owned ``/root/.axon_site``); the session therefore writes a
sourceable env file at ``<lock>.env`` (``export JAX_PLATFORMS=cpu`` +
``unset PALLAS_AXON_POOL_IPS`` — the env pin alone is NOT enough for a
fresh interpreter here, see tools/chip_session.sh) for ad-hoc shells,
and relay probes go through ``tools/probe.py``, which refuses to probe
while the flock is held (VERDICT r4 item 4).

Reference analog: TF's in-process cluster tests serialize device access
via per-test servers ($TF multi_worker_test_base.py); the single tunneled
lease needs the same exclusion made explicit.
"""

from __future__ import annotations

import os
import time

__all__ = ["lock_path", "lock_holder", "pin_cpu_if_locked",
           "pin_is_current", "PIN_MAX_AGE_S"]

_DEFAULT_LOCK = "/tmp/dtf_chip_session.lock"

#: how long a CPU-pin stamp inherited from an ANCESTOR process is still
#: believed to describe a live session (ADVICE r5: DTF_CHIP_PINNED
#: propagates to descendants indefinitely). Generously above the ~41-min
#: window the on-chip tiering runs in.
PIN_MAX_AGE_S = 3600.0


def lock_path() -> str:
    return os.environ.get("DTF_CHIP_LOCK", _DEFAULT_LOCK)


def lock_holder(_retry: bool = True) -> int | None:
    """Pid of the live chip-session holder, or None (no lock / stale /
    held by this process tree).

    Liveness: when the session's flock sidecar exists, probe the kernel
    flock itself — held means a live session even through SIGKILL/pid
    churn (the kernel releases flocks on process death, so a killed
    session reads as stale no matter what pid now owns the recorded
    number). Without the sidecar (hand-written lock file, tests), fall
    back to pid liveness."""
    if os.environ.get("DTF_CHIP_SESSION") == "1":
        return None  # we ARE the session (or one of its children)
    try:
        with open(lock_path()) as f:
            pid = int(f.read().strip() or "0")
    except (OSError, ValueError):
        return None
    if pid <= 0 or pid == os.getpid():
        return None

    flock_path = lock_path() + ".flock"

    def _stale(sidecar: bool = False) -> None:
        try:  # killed session left the file behind: clean up best-effort
            os.unlink(lock_path())
        except OSError:
            pass
        if sidecar:
            # Also drop the orphaned sidecar: a later hand-written pid
            # file next to it would otherwise be judged solely by the
            # flock probe forever (ADVICE r4). Only when the kernel lock
            # was just observed acquirable — a held flock is a live
            # session and its sidecar must survive.
            try:
                os.unlink(flock_path)
            except OSError:
                pass

    if os.path.exists(flock_path):
        import fcntl

        try:
            with open(flock_path) as fl:
                fcntl.flock(fl, fcntl.LOCK_EX | fcntl.LOCK_NB)
                # acquirable => no session holds THIS inode. But between
                # our open and the flock, another checker may have
                # unlinked it and a NEW session recreated + locked a
                # fresh sidecar — unlinking the path now would delete
                # the LIVE session's files (the same TOCTOU
                # chip_session.sh closes with its -ef verify). Only
                # clean up when the locked fd still IS the path.
                try:
                    st_fd, st_path = os.fstat(fl.fileno()), os.stat(flock_path)
                    current = (st_fd.st_dev, st_fd.st_ino) == \
                              (st_path.st_dev, st_path.st_ino)
                except OSError:
                    current = False  # path gone: nothing to clean
                if current:
                    _stale(sidecar=True)
                    return None
                if _retry:  # sidecar replaced under us: re-evaluate once
                    return lock_holder(_retry=False)
                return pid  # unsettled race: CPU pin is the safe default
        except BlockingIOError:
            return pid  # genuinely held by a live session
        except OSError:
            pass  # unreadable sidecar: fall through to pid liveness
    try:
        os.kill(pid, 0)  # liveness probe, no signal delivered
    except ProcessLookupError:
        _stale()
        return None
    except PermissionError:
        pass  # alive, owned by another uid — still counts as held
    return pid


def pin_is_current(max_age_s: float = PIN_MAX_AGE_S) -> bool:
    """Is the inherited CPU-pin stamp still evidence of a live chip
    session?

    True when :func:`pin_cpu_if_locked` pinned THIS process (the
    decision and its consumer share a lifetime), or when an ancestor's
    pin is younger than ``max_age_s``. A sweep driver pinned during a
    session that spawns a bench child hours after the session ended
    must NOT stamp ``chip_session_live`` on that child's row (ADVICE
    r5) — its stale stamp reads False here. A pre-timestamp stamp
    (legacy ``DTF_CHIP_PINNED=1`` with no ``_AT``) from another process
    is treated as stale for the same reason."""
    if os.environ.get("DTF_CHIP_PINNED") != "1":
        return False
    if os.environ.get("DTF_CHIP_PINNED_PID") == str(os.getpid()):
        return True  # we made the pin decision ourselves, this run
    try:
        age = time.time() - float(os.environ["DTF_CHIP_PINNED_AT"])
    except (KeyError, ValueError):
        return False
    return 0 <= age <= max_age_s


def pin_cpu_if_locked(log=None) -> bool:
    """Pin this process to the CPU backend when a live chip session owns
    the lease. Must run before the first backend init to take effect
    (jax backends initialize lazily). Returns True when pinned.

    Deliberately overrides even an explicit JAX_PLATFORMS pin: the lock
    exists precisely for the moment operator discipline fails, and CPU
    is always safe for the pinned process while the alternative can
    wedge the device lease for the measurement session.
    """
    pid = lock_holder()
    if pid is None:
        return False
    if log is None:
        def log(s):  # stderr, not stdout: callers may parse stdout JSON
            import sys
            print(s, file=sys.stderr)
    log(f"chip-session lock held by live pid {pid} "
        f"({lock_path()}); pinning this process to CPU")
    os.environ["JAX_PLATFORMS"] = "cpu"
    # Record WHY this process tree is CPU-pinned, at the moment the
    # decision is made: consumers (bench.py's chip_session_live stamp)
    # must not re-probe the lock later — the session can start/stop in
    # between and flip the answer (review r5). The deciding pid and a
    # timestamp ride along so long-lived process trees can bound the
    # stamp's validity (pin_is_current, ADVICE r5): the env var itself
    # is inherited by every descendant forever.
    os.environ["DTF_CHIP_PINNED"] = "1"
    os.environ["DTF_CHIP_PINNED_PID"] = str(os.getpid())
    os.environ["DTF_CHIP_PINNED_AT"] = repr(time.time())
    # Children too: a fresh interpreter ignores the env pin (the axon
    # sitecustomize overrides it — see tools/chip_session.sh), so also
    # drop the bootstrap gate from anything this process spawns.
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception as e:  # jax absent/odd: env var alone still helps
        log(f"  (jax config update skipped: {e})")
    return True
