"""Shared benchmark-harness scaffolding (bench.py, tools/bench_bert.py).

The load-bearing pieces every throughput harness in this repo must agree
on, extracted so they cannot drift between benchmarks:

- **Platform detection** that never mistakes a tunneled accelerator for
  CPU: axon-relayed chips report ``platform="tpu"`` / ``device_kind="TPU
  v5 lite"``, so both are checked (a miss would silently bench the tiny
  CPU-fallback model and report it as the real number).
- **Execution-forcing sync**: on tunneled platforms ``jax.block_until_
  ready`` returns before the computation runs, inflating step rates
  ~40x. Only fetching a VALUE that data-depends on every measured step
  (the chained loss) proves the work happened.
- **Warmup/measure loop** with the sync applied once at each boundary,
  and a finite-loss assertion so a diverged/never-ran step can't post a
  throughput number.

Reference analog: the reference harness read its throughput off
``StepCounterHook`` logs ($TF basic_session_run_hooks.py:674); the
value-fetch discipline here is the TPU-async-dispatch replacement for
TF-session's synchronous ``run()`` returning fetched tensors.
"""

from __future__ import annotations

import time
from typing import Any, Callable

import jax
import numpy as np

__all__ = [
    "honor_env_platform", "describe_devices", "sync_by_value",
    "timed_steps", "fall_back_to_cpu_if_unreachable",
    "probe_cache_path", "read_probe_cache", "write_probe_cache",
]


def probe_cache_path() -> str:
    """Location of the shared relay-probe cache (watcher + bench
    harnesses agree through DTF_PROBE_CACHE)."""
    import os

    return os.environ.get("DTF_PROBE_CACHE", "/tmp/dtf_relay_probe.json")


def read_probe_cache(ttl_s: float) -> bool | None:
    """Last relay-probe verdict if fresh: True (healthy) / False (down) /
    None (no cache, stale, or unreadable).

    The watcher probes every few minutes and records each verdict via
    :func:`write_probe_cache`; the driver-invoked bench must not burn a
    scarce healthy window re-deriving what the watcher just measured
    (VERDICT r4 weak #1), nor hang 150 s re-discovering a dead relay.

    Ownership gate (ADVICE r5): the default cache lives in
    world-writable /tmp, so a verdict is only believed when the file is
    owned by this uid — any other user (or stray process) writing
    ``{"healthy": false}`` could otherwise silently pin every bench to
    CPU for ``ttl_s`` (a poisoned DOWN is believed outright; a stale
    HEALTHY is at least confirm-probed). Foreign-owned caches read as
    "no cache", which falls through to a real probe.
    """
    import json
    import os

    try:
        with open(probe_cache_path()) as f:
            # fstat the open handle, not the path: no window for a swap
            # between the ownership check and the read
            if os.fstat(f.fileno()).st_uid != os.getuid():
                return None
            rec = json.load(f)
        age = time.time() - float(rec["ts"])
        if 0 <= age <= ttl_s:
            return bool(rec["healthy"])
    except (OSError, ValueError, KeyError, TypeError):
        pass
    return None


def write_probe_cache(healthy: bool, source: str = "probe") -> None:
    """Record a relay-probe verdict (atomic rename; best-effort)."""
    import json
    import os

    path = probe_cache_path()
    try:
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump({"ts": time.time(), "healthy": bool(healthy),
                       "source": source}, f)
        os.replace(tmp, path)
    except OSError:
        pass


def honor_env_platform() -> None:
    """Make an explicit ``JAX_PLATFORMS`` env var win even though the
    site plugin may have overridden the config default at import time
    (parallel/cluster.py note)."""
    import os

    env = os.environ.get("JAX_PLATFORMS")
    if env and jax.config.jax_platforms != env:
        jax.config.update("jax_platforms", env)
    # The chip-session lock outranks any pin: a concurrent process must
    # never contend for the single tunneled device lease (chip_lock.py).
    from .chip_lock import pin_cpu_if_locked

    pin_cpu_if_locked()


# The one probe payload every harness agrees on (tools/probe.py runs the
# same bytes): init the backend, ASSERT the accelerator platform (a
# silent CPU fallback must read as DOWN, never as healthy-in-cache), and
# force one tiny jit through the relay — init alone can succeed while
# the compile path is wedged (round-3 remote_compile HTTP 500s).
PROBE_PAYLOAD = (
    "import jax, jax.numpy as jnp\n"
    "d = jax.devices()\n"
    "assert d and (d[0].platform == 'tpu'\n"
    "              or getattr(d[0], 'device_kind', '')"
    ".upper().startswith('TPU')), d\n"
    "print('PROBE-OK', d,\n"
    "      float(jax.jit(lambda a: (a @ a).sum())"
    "(jnp.ones((256, 256), jnp.bfloat16))))\n"
)


def _probe_subprocess(timeout_s: float, log) -> bool | None:
    """One relay probe (PROBE_PAYLOAD) in a subprocess under an external
    timeout. True = healthy, False = init/compile failed or wrong
    platform, None = hung past the timeout (backend init BLOCKS forever
    when the relay is down; the killed child never acquired a device
    lease)."""
    import subprocess
    import sys

    try:
        proc = subprocess.run(
            [sys.executable, "-u", "-c", PROBE_PAYLOAD],
            timeout=timeout_s, capture_output=True, text=True,
        )
        if proc.returncode == 0:
            for line in proc.stdout.strip().splitlines()[-1:]:
                log(line)  # the PROBE-OK line: devices + jit result
            return True
        log("accelerator probe failed; stderr tail:")
        for line in proc.stderr.splitlines()[-5:]:
            log("  " + line)
        return False
    except subprocess.TimeoutExpired:
        log(f"accelerator probe hung >{timeout_s}s (relay down?)")
        return None


def probe_with_retry(timeout_s: float, log=lambda s: None,
                     first_timeout_s: float | None = None) -> bool:
    """THE relay-probe policy, shared by the bench ladder and
    tools/probe.py so cache semantics cannot drift: run PROBE_PAYLOAD,
    believe any definitive verdict at once, and retry a single HANG at
    the full budget — a lone slow probe must not read as a dead relay.
    ``first_timeout_s`` lets the cached-healthy path use a short
    confirming budget for the first attempt."""
    verdict = _probe_subprocess(first_timeout_s or timeout_s, log)
    if verdict is None:
        verdict = _probe_subprocess(timeout_s, log)
    return verdict is True


def fall_back_to_cpu_if_unreachable(timeout_s: int = 90,
                                    log=lambda s: None,
                                    ttl_s: float = 480.0) -> bool:
    """Pin this process to CPU when the tunneled accelerator is
    unreachable (the axon relay has died mid-session repeatedly —
    PERF_NOTES.md). Decision ladder, cheapest evidence first:

    1. An explicit non-ambient ``JAX_PLATFORMS`` pin or
       ``BENCH_SKIP_PROBE=1`` wins untouched (sweeps/retries that
       already know the relay state).
    2. A LIVE chip-session lock pins CPU immediately — the probe itself
       is a bare device init and would contend for the single lease
       (the round-3 collision class; chip_lock.py).
    3. A fresh watcher probe verdict (``write_probe_cache``, TTL
       ``ttl_s``): "down" falls back with zero probe latency; "healthy"
       still runs one SHORT confirming probe (the relay can die within
       the TTL, and trusting a stale "healthy" would hang the driver's
       backend init forever — a lost row, worse than a CPU row).
    4. No/stale cache: probe at ``timeout_s``, retrying a hang once
       (VERDICT r4 item 3 — don't lose a real window to one slow probe).

    The default ``ttl_s`` covers one full watcher cycle in the worst
    (outage) case — 240 s sleep + up to 180 s of hung probe — plus a
    real margin for interpreter/subprocess overhead per cycle, so a
    DOWN verdict stays fresh across it and the driver never re-pays the
    180 s discovery; a HEALTHY verdict that old is still confirm-probed.

    Every probe verdict is written back to the cache for the next
    harness in line. Returns True when the CPU fallback was applied."""
    import os

    env_pin = os.environ.get("JAX_PLATFORMS", "").strip()
    if env_pin not in ("", "axon"):
        return False
    if os.environ.get("BENCH_SKIP_PROBE") == "1":
        return False

    from .chip_lock import pin_cpu_if_locked

    if pin_cpu_if_locked(log=log):
        return True

    def fall_back() -> bool:
        log("falling back to CPU")
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")
        return True

    def healthy() -> bool:
        # cache the healthy result for this process tree: children
        # (sweeps, retries) skip the duplicate backend-init probe
        os.environ["BENCH_SKIP_PROBE"] = "1"
        write_probe_cache(True, source="bench")
        return False

    cached = read_probe_cache(ttl_s)
    if cached is False:
        log(f"relay probe cache says DOWN (<{ttl_s:.0f}s old); "
            "skipping the probe")
        return fall_back()
    if cached is True:
        log(f"relay probe cache says healthy (<{ttl_s:.0f}s old); "
            "running short confirming probe")
        # short first budget; probe_with_retry keeps a hung confirm from
        # poisoning the shared cache without a full-budget second look
        if probe_with_retry(timeout_s, log,
                            first_timeout_s=min(45.0, timeout_s)):
            return healthy()
        write_probe_cache(False, source="bench")
        return fall_back()

    # No/stale cache: full-budget probe. Healthy init through the relay
    # is ~16-20 s measured (r3 probe.log, r5 transcripts), so 90 s is
    # already a generous multiple; two hangs are a dead relay, not a
    # slow one.
    ok = probe_with_retry(timeout_s, log)
    write_probe_cache(ok, source="bench")
    if ok:
        return healthy()
    return fall_back()


def describe_devices() -> tuple[list, int, str, bool]:
    """(devices, n_chips, platform, on_tpu) — robust TPU detection for
    tunneled platforms (see module docstring)."""
    devices = jax.devices()
    platform = devices[0].platform
    kind = getattr(devices[0], "device_kind", "")
    on_tpu = platform == "tpu" or kind.upper().startswith("TPU")
    return devices, len(devices), platform, on_tpu


def sync_by_value(metrics: dict) -> float:
    """Force execution of every step the loss data-depends on by
    fetching its value; returns the loss as a host float."""
    return float(jax.device_get(metrics["loss"]))


def timed_steps(
    step: Callable[[Any, Any], tuple[Any, dict]],
    state: Any,
    next_batch: Callable[[], Any],
    *,
    warmup: int,
    measured: int,
    log: Callable[[str], None] = lambda s: None,
) -> tuple[Any, float, float]:
    """Warmup then time ``measured`` chained steps.

    ``next_batch`` is called once per step (return the same resident
    batch for a device-throughput window, or pull from a prefetcher for
    a pipeline-fed window). Returns ``(state, steps_per_sec, loss)``;
    asserts the final loss is finite so a broken run cannot post a rate.
    """
    log("compiling + warmup...")
    metrics = None
    for _ in range(warmup):
        state, metrics = step(state, next_batch())
    if metrics is not None:  # warmup=0: nothing dispatched yet to sync
        sync_by_value(metrics)
    log("measuring...")
    t0 = time.perf_counter()
    for _ in range(measured):
        state, metrics = step(state, next_batch())
    loss = sync_by_value(metrics)
    dt = time.perf_counter() - t0
    log(f"final loss {loss:.4f} (finite => really trained)")
    # explicit raise, not assert: must survive `python -O` so a diverged
    # run can never post a throughput number
    if not np.isfinite(loss):
        raise RuntimeError(f"non-finite loss {loss}; refusing to report a rate")
    return state, measured / dt, loss
