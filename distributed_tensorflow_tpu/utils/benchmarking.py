"""Shared benchmark-harness scaffolding (bench.py, tools/bench_bert.py).

The load-bearing pieces every throughput harness in this repo must agree
on, extracted so they cannot drift between benchmarks:

- **Platform detection** that never mistakes a tunneled accelerator for
  CPU: axon-relayed chips report ``platform="tpu"`` / ``device_kind="TPU
  v5 lite"``, so both are checked (a miss would silently bench the tiny
  CPU-fallback model and report it as the real number).
- **Execution-forcing sync**: on tunneled platforms ``jax.block_until_
  ready`` returns before the computation runs, inflating step rates
  ~40x. Only fetching a VALUE that data-depends on every measured step
  (the chained loss) proves the work happened.
- **Warmup/measure loop** with the sync applied once at each boundary,
  and a finite-loss assertion so a diverged/never-ran step can't post a
  throughput number.

Reference analog: the reference harness read its throughput off
``StepCounterHook`` logs ($TF basic_session_run_hooks.py:674); the
value-fetch discipline here is the TPU-async-dispatch replacement for
TF-session's synchronous ``run()`` returning fetched tensors.
"""

from __future__ import annotations

import time
from typing import Any, Callable

import jax
import numpy as np

__all__ = [
    "honor_env_platform", "describe_devices", "sync_by_value",
    "timed_steps", "fall_back_to_cpu_if_unreachable",
]


def honor_env_platform() -> None:
    """Make an explicit ``JAX_PLATFORMS`` env var win even though the
    site plugin may have overridden the config default at import time
    (parallel/cluster.py note)."""
    import os

    env = os.environ.get("JAX_PLATFORMS")
    if env and jax.config.jax_platforms != env:
        jax.config.update("jax_platforms", env)
    # The chip-session lock outranks any pin: a concurrent process must
    # never contend for the single tunneled device lease (chip_lock.py).
    from .chip_lock import pin_cpu_if_locked

    pin_cpu_if_locked()


def fall_back_to_cpu_if_unreachable(timeout_s: int = 150,
                                    log=lambda s: None) -> bool:
    """Pin this process to CPU when the tunneled accelerator is
    unreachable (the axon relay has died mid-session repeatedly —
    PERF_NOTES.md). Backend init BLOCKS forever when the relay is down,
    so the probe runs device init in a subprocess under an external
    timeout; the killed child never acquired a device lease.

    Only the ambient platform config ("axon" baked into the environment,
    or unset) falls back; an operator's explicit JAX_PLATFORMS pin is
    honored untouched. BENCH_SKIP_PROBE=1 skips the probe (sweeps/
    retries that already know the relay state). Returns True when the
    fallback was applied."""
    import os
    import subprocess
    import sys

    env_pin = os.environ.get("JAX_PLATFORMS", "").strip()
    if env_pin not in ("", "axon"):
        return False
    if os.environ.get("BENCH_SKIP_PROBE") == "1":
        return False
    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s, capture_output=True, text=True,
        )
        if proc.returncode == 0:
            # cache the healthy result for this process tree: children
            # (sweeps, retries) skip the duplicate backend-init probe
            os.environ["BENCH_SKIP_PROBE"] = "1"
            return False
        log("accelerator probe failed; falling back to CPU. stderr tail:")
        for line in proc.stderr.splitlines()[-5:]:
            log("  " + line)
    except subprocess.TimeoutExpired:
        log(f"accelerator probe hung >{timeout_s}s (relay down?); "
            "falling back to CPU")
    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")
    return True


def describe_devices() -> tuple[list, int, str, bool]:
    """(devices, n_chips, platform, on_tpu) — robust TPU detection for
    tunneled platforms (see module docstring)."""
    devices = jax.devices()
    platform = devices[0].platform
    kind = getattr(devices[0], "device_kind", "")
    on_tpu = platform == "tpu" or kind.upper().startswith("TPU")
    return devices, len(devices), platform, on_tpu


def sync_by_value(metrics: dict) -> float:
    """Force execution of every step the loss data-depends on by
    fetching its value; returns the loss as a host float."""
    return float(jax.device_get(metrics["loss"]))


def timed_steps(
    step: Callable[[Any, Any], tuple[Any, dict]],
    state: Any,
    next_batch: Callable[[], Any],
    *,
    warmup: int,
    measured: int,
    log: Callable[[str], None] = lambda s: None,
) -> tuple[Any, float, float]:
    """Warmup then time ``measured`` chained steps.

    ``next_batch`` is called once per step (return the same resident
    batch for a device-throughput window, or pull from a prefetcher for
    a pipeline-fed window). Returns ``(state, steps_per_sec, loss)``;
    asserts the final loss is finite so a broken run cannot post a rate.
    """
    log("compiling + warmup...")
    metrics = None
    for _ in range(warmup):
        state, metrics = step(state, next_batch())
    if metrics is not None:  # warmup=0: nothing dispatched yet to sync
        sync_by_value(metrics)
    log("measuring...")
    t0 = time.perf_counter()
    for _ in range(measured):
        state, metrics = step(state, next_batch())
    loss = sync_by_value(metrics)
    dt = time.perf_counter() - t0
    log(f"final loss {loss:.4f} (finite => really trained)")
    # explicit raise, not assert: must survive `python -O` so a diverged
    # run can never post a throughput number
    if not np.isfinite(loss):
        raise RuntimeError(f"non-finite loss {loss}; refusing to report a rate")
    return state, measured / dt, loss
