"""Analytic FLOPs + MFU accounting (SURVEY.md §5.5, §6 reporting rules).

MFU is computed from *analytic* model FLOPs — the model's own arithmetic
count, not profiler-counted device FLOPs (which flatter recompute). Peak
chip FLOP/s comes from a table keyed on jax's device_kind, overridable via
config for new hardware.

FRAMEWORK-WIDE CONTRACT (round-2 unification, VERDICT.md item 2): every
model's ``flops_per_example`` and every workload's
``WorkloadParts.flops_per_step`` are FORWARD-only. The fwd+bwd training
multiplier (``train_flops_multiplier()``, ×3) is applied in exactly ONE
consumer site: ``obs/goodput.train_mfu`` — the shared MFU helper that
``MetricsLogger`` (train-loop MFU), ``bench.py``, and the family
benches all route through, and which publishes the ``mfu`` gauge.
``tests/test_flops_contract.py`` enforces both halves.
"""

from __future__ import annotations

import jax

# Peak dense bf16 FLOP/s per chip (public spec-sheet numbers).
PEAK_FLOPS_BY_KIND: dict[str, float] = {
    # TPU
    "TPU v2": 45e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,  # v5p
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,  # trillium
    "TPU v6e": 918e12,
    # CPU fake devices in tests: arbitrary small constant so MFU math runs.
    "cpu": 1e12,
}


def peak_flops_per_chip(device: jax.Device | None = None) -> float:
    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "cpu")
    for key, val in PEAK_FLOPS_BY_KIND.items():
        if kind.lower().startswith(key.lower()):
            return val
    return PEAK_FLOPS_BY_KIND.get(kind, 1e12)


def mfu(model_flops_per_step: float, steps_per_sec: float, n_chips: int,
        peak_per_chip: float | None = None) -> float:
    """model FLOPs/step × steps/s ÷ (chips × peak) — the §6 honesty rule."""
    if peak_per_chip is None:
        peak_per_chip = peak_flops_per_chip()
    return model_flops_per_step * steps_per_sec / (n_chips * peak_per_chip)


def dense_flops(m: int, n: int, k: int) -> float:
    """Forward FLOPs of an (m,k)@(k,n) matmul."""
    return 2.0 * m * n * k


def conv2d_flops(batch: int, out_h: int, out_w: int, out_c: int,
                 in_c: int, kh: int, kw: int) -> float:
    return 2.0 * batch * out_h * out_w * out_c * in_c * kh * kw


def train_flops_multiplier() -> float:
    """fwd + bwd ≈ 3× fwd for dense nets (bwd does two matmuls per fwd one)."""
    return 3.0


def transformer_flops_per_token(n_params: float, seq_len: int,
                                n_layers: int, d_model: int) -> float:
    """Forward FLOPs/token ≈ 2·N_params + attention term 2·L·s·d (scores+AV,
    the 2 matmuls each 2·s·d per token, halved for causal ≈ kept full here)."""
    return 2.0 * n_params + 4.0 * n_layers * seq_len * d_model
