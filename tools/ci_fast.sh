#!/usr/bin/env bash
# Fast CI tier — the gates that run in seconds, before the full suite:
#
#   1. tools/smoke_collect.sh  — pytest --collect-only import gate
#      (catches package-wide import regressions, ISSUE 1)
#   2. tools/obs_check.py      — telemetry smoke: registry → Prometheus
#      exposition render → format lint → JSONL round-trip (ISSUE 2)
#   3. tools/dtf_lint.py       — framework-aware static analysis
#      (ISSUE 7, v2 engine ISSUE 10, v3 sharding auditor ISSUE 14):
#      --self-check first (every rule — a rule with NO fixture is
#      itself a self-check failure — must still fire on its shipped
#      fixtures, so the gate cannot rot silently), then the --strict
#      tree lint with all 11 rules (host-sync-in-step and
#      donation-after-use on the cross-module call graph, plus
#      lock-discipline, closed-vocab, exception-hygiene,
#      wall-clock-in-seam, atomic-durable-write, metric-naming, and
#      the v3 partitioning family — shard-rules-coverage totality/
#      liveness of every partition_rules table, mesh-axis-closed-vocab
#      over every PartitionSpec/collective axis literal, and
#      sharding-seam-bypass confining placement construction to
#      parallel/sharding.py — must all be clean over the package,
#      tools, and bench.py; an injected unmatched param or out-of-
#      vocab axis fails here), then the determinism rule alone over
#      tests/ — the chaos/replay oracles must not consume ambient
#      entropy either (relaxed set: pure test scaffolding is exempt
#      from everything but determinism)
#   4. tools/sweep.py --dryrun — scaling-observatory smoke (ISSUE 11):
#      a 3-cell mesh×workload sweep (mlp × {1dev, dp8, pod2_dp2} on 8
#      fake CPU devices — pod2_dp2 exercises the two-level PodTopology
#      descriptor, ISSUE 19) that must emit a schema-valid
#      dtf-scaling-1 report,
#      every cell provenance-stamped (--expect-platform cpu is the
#      masquerade tripwire: the report must SAY cpu when it ran on
#      cpu), with the 8-dev dp scaling-efficiency gate enforced
#   5. tools/chaos_smoke.py    — resilience smoke: scheduler
#      timeout/cancel/backpressure invariants + one SIGTERM →
#      coordinated-save → resume subprocess round (ISSUE 3) + one
#      supervised SIGTERM + corrupt-newest-checkpoint run that must
#      recover via fallback restore and finish finite (ISSUE 4) + one
#      nan-blame round: a recurring NaN batch skipped in-graph, blamed
#      and quarantined, with the restart replaying around the hole
#      (ISSUE 9) + one fleet gang-restart round: a hung worker detected
#      by missed heartbeats, whole-gang SIGTERM/SIGKILL, incarnation
#      bump, and a relaunch from the latest common valid checkpoint
#      (ISSUE 8) + one ELASTIC round: one of 3 workers hard-dies, the
#      gang shrinks at a barrier instead of stopping, the relaunched
#      replacement rejoins at the next barrier, and restart_recovery
#      waste beats the gang-restart baseline by >= 10x (ISSUE 12) +
#      one serve-fleet failover round: a serve replica SIGKILLed
#      mid-stream, in-flight requests requeued and re-prefilled on the
#      survivor, every stream finished, survivors leak-free (ISSUE 16) +
#      one P2P CATCH-UP round (ISSUE 18): the same elastic death, but the
#      replacement pulls the newest common valid checkpoint from a live
#      survivor over the file control plane instead of replaying — rejoin
#      wall must beat the replay baseline measured in the same run, and
#      every worker's final params must be bit-identical to an
#      uninterrupted same-seed run + one ASYNC-KILL round (ISSUE 18): a
#      worker SIGKILLed INSIDE the async checkpoint commit window — the
#      torn step must be invisible (no .corrupt quarantine, no .pending
#      residue) and the gang must strict-restore the previous step
#   6. tools/postmortem.py     — flight-recorder gates: the supervised
#      round's postmortem dump must pass schema validation AND contain
#      fault → preemption save → restart → quarantine → fallback-restore
#      in causal order (ISSUE 6), the nan-blame round's dump must tell
#      the anomaly story — nan fault → in-graph skip → blame →
#      restart restore (ISSUE 9) — and the fleet round's dump the
#      gang-restart story — worker dead → gang stop → fallback
#      ckpt_restore → fleet restart — in causal order (ISSUE 8), and
#      the elastic round's dump the resize story — worker dead →
#      fleet_shrink → fleet_rejoin → fleet_done (ISSUE 12)
#   6b. tools/postmortem.py --merge + tools/fleet_top.py — fleet
#      observatory gates (ISSUE 15): the chaos fleet and elastic rounds
#      stage every process's flight-recorder dump (plus telemetry
#      snapshots and heartbeats) under artifacts/{fleet,elastic}_dumps;
#      the merge gate aligns the per-process clocks on control-plane
#      anchors and asserts the CROSS-WORKER causal stories, and
#      fleet_top --once exercises the merged text view on the same
#      artifacts
#   7. tools/bench_serve.py  — paged-KV serve smoke (ISSUE 13, spec
#      decoding ISSUE 20): the mixed-length chaos preset on the tiny
#      model with speculative decoding on (--spec-k 4), chaos epilogue
#      included, gating (a) 64-step greedy parity of BOTH paged
#      attention impls against the dense fallback plus the spec ==
#      non-spec greedy stream pins, short and multi-chunk-long prompts
#      (--parity-check), (b) leak-free shutdown (the block allocator
#      back to all-free after drain, spec rollback included), (c)
#      full-batch occupancy under backlog + the one-chunk starvation
#      bound for resident decoders, and (d) the same-run speculation
#      win: chaos throughput must beat the non-spec gather baseline
#      measured in the same process (--min-speedup — the bar is LOW
#      because the CI preset is tiny and noisy; the honest numbers
#      live in PERF_NOTES.md)
#   7d. tools/bench_trend.py — serve perf-regression sentinel
#      (ISSUE 20): same freshest-pair trend as 4b, over the serve
#      chaos bench — when a previous run left
#      artifacts/serve_chaos_prev.json, the fresh run's tokens/sec
#      must not collapse past the budget
#   7b. tools/postmortem.py --merge — serve-fleet failover gate
#      (ISSUE 16): chaos_smoke's serve-fleet round SIGKILLs one of two
#      serve/replica.py subprocesses mid-stream and stages the
#      per-process dumps under artifacts/serve_fleet_dumps; the merge
#      aligns replica clocks on the serve_route dispatch/ACK handshake
#      and asserts replica-dead -> lane-head requeue -> survivor
#      re-admission -> fleet_done
#   6c. tools/postmortem.py --merge — async-durability gates (ISSUE 18):
#      the async-kill round's merged timeline must show the torn-write
#      invisibility story — ckpt_async_begin → fault_fired
#      [fault=async_commit_kill] → ckpt_restore[fallback=False] (the
#      restore is STRICT: nothing to fall back from, the torn step never
#      became visible) — and the p2p round's timeline the catch-up story:
#      worker dead → survivor catchup_offer → joiner catchup_restore →
#      fleet_rejoin, with no catchup_fallback
#   4b. tools/bench_trend.py — perf-regression sentinel (ISSUE 18): when
#      a previous run left artifacts/scaling_dryrun_prev.json, compare
#      the fresh sweep's dp8-cell steps/sec against it (provenance-
#      checked: same platform/device_kind, both git_sha-pinned) and fail
#      on a drop past the budget; first run on a clean tree skips
#   6d. tools/postmortem.py --merge — hierarchical fault-domain gates
#      (ISSUE 19): chaos_smoke's two-pod outage round SIGKILLs all of
#      pod B mid-run while pod A keeps stepping — the merged timeline
#      must show pod_outage → pod-local restart (each pod-B worker
#      strict-restoring at pod B's OWN quorum, fallback=False) →
#      pod_rejoin, with no global gang stop; the partition round freezes
#      pod B's heartbeat file while the process stays alive — the
#      supervisor must FENCE (no restart, no split-brain), unfence on
#      heal, and judge the slow-beat pod LIVE throughout
#   7c. tools/trace_view.py — request-ledger gate (ISSUE 17): merge the
#      same round's per-process request traces (router + both replica
#      incarnations, including the SIGKILLed victim's surviving
#      per-pump dump) into ONE per-request timeline, require a killed
#      request's merged trace to carry the FULL causal chain — submit →
#      route → admit → prefill → first token → death-requeue → re-route
#      → re-admit → re-prefill → token → finish — with spans from at
#      least two distinct replica processes, and render the slowest-k
#      tail-attribution report (phase durations must sum to measured
#      TTFT within 1%)
#
# Usage: tools/ci_fast.sh   (extra args are passed to smoke_collect)
set -euo pipefail
cd "$(dirname "$0")/.."
bash tools/smoke_collect.sh "$@"
env JAX_PLATFORMS=cpu python tools/obs_check.py >/dev/null
env JAX_PLATFORMS=cpu python tools/dtf_lint.py --self-check
env JAX_PLATFORMS=cpu python tools/dtf_lint.py --strict \
  distributed_tensorflow_tpu tools bench.py
env JAX_PLATFORMS=cpu python tools/dtf_lint.py --strict \
  --rules wall-clock-in-seam tests
# keep the previous sweep report around as the bench_trend baseline:
# the freshest pair of runs IS the trend (ISSUE 18)
if [ -f artifacts/scaling_dryrun.json ]; then
  cp artifacts/scaling_dryrun.json artifacts/scaling_dryrun_prev.json
fi
env JAX_PLATFORMS=cpu \
  XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8" \
  python tools/sweep.py --dryrun --expect-platform cpu \
  --out artifacts/scaling_dryrun.json >/dev/null
# perf-regression sentinel (ISSUE 18): dryrun throughput on shared CI
# hosts is noisy, so the budget is generous — this catches collapses
# (a serialization bug halving step rate), not percent-level drift
if [ -f artifacts/scaling_dryrun_prev.json ]; then
  env JAX_PLATFORMS=cpu python tools/bench_trend.py \
    artifacts/scaling_dryrun_prev.json artifacts/scaling_dryrun.json \
    --metric cells.0.steps_per_sec --max-regress-pct 60
fi
env JAX_PLATFORMS=cpu python tools/chaos_smoke.py
env JAX_PLATFORMS=cpu python tools/postmortem.py \
  "${DTF_CHAOS_POSTMORTEM:-artifacts/chaos_postmortem.jsonl}" --quiet \
  --expect 'fault_fired[fault=sigterm],ckpt_save[trigger=preemption],sup_restart,fault_fired[fault=ckpt_corrupt],ckpt_quarantine,ckpt_restore[fallback=True]'
env JAX_PLATFORMS=cpu python tools/postmortem.py \
  "${DTF_ANOMALY_POSTMORTEM:-artifacts/anomaly_postmortem.jsonl}" --quiet \
  --expect 'fault_fired[fault=nan_batch],anomaly_skip,anomaly_blame,ckpt_restore'
env JAX_PLATFORMS=cpu python tools/postmortem.py \
  "${DTF_FLEET_POSTMORTEM:-artifacts/fleet_postmortem.jsonl}" --quiet \
  --expect 'fleet_worker_dead,fleet_gang_stop,ckpt_restore[fallback=True],fleet_restart,fleet_done'
env JAX_PLATFORMS=cpu python tools/postmortem.py \
  "${DTF_ELASTIC_POSTMORTEM:-artifacts/elastic_postmortem.jsonl}" --quiet \
  --expect 'fleet_worker_dead,fleet_shrink,fleet_rejoin,fleet_done'
# fleet observatory (ISSUE 15): re-merge the chaos rounds' per-process
# dumps into ONE cross-worker timeline (clock alignment anchored on the
# control-plane handshakes) and gate the CROSS-PROCESS causal stories —
# the gang stop precedes every worker's restore; the shrink release
# precedes every survivor's application of the new sharding
env JAX_PLATFORMS=cpu python tools/postmortem.py --merge \
  "${DTF_FLEET_DUMPS:-artifacts/fleet_dumps}"/fleet.jsonl \
  "${DTF_FLEET_DUMPS:-artifacts/fleet_dumps}"/flightrec-w*.jsonl \
  --out "${DTF_FLEET_MERGED:-artifacts/fleet_merged_postmortem.jsonl}" --quiet \
  --expect 'fleet_gang_stop,ckpt_restore[src=w0i2],fleet_restart,fleet_done' \
  --expect 'fleet_gang_stop,ckpt_restore[src=w1i2],fleet_restart,fleet_done'
env JAX_PLATFORMS=cpu python tools/postmortem.py --merge \
  "${DTF_ELASTIC_DUMPS:-artifacts/elastic_dumps}"/fleet.jsonl \
  "${DTF_ELASTIC_DUMPS:-artifacts/elastic_dumps}"/flightrec-w*.jsonl \
  --out "${DTF_ELASTIC_MERGED:-artifacts/elastic_merged_postmortem.jsonl}" --quiet \
  --expect 'fleet_worker_dead,fleet_hold,elastic_hold[src=w0i1],fleet_shrink,elastic_release[src=w0i1],fleet_rejoin,fleet_done' \
  --expect 'fleet_worker_dead,fleet_hold,elastic_hold[src=w2i1],fleet_shrink,elastic_release[src=w2i1],fleet_rejoin,fleet_done' \
  --expect 'fleet_shrink,elastic_release[src=w1i1],fleet_rejoin,fleet_done'
# async durability (ISSUE 18): the async-kill round's merged timeline
# must show the torn step was INVISIBLE — the victim began an async
# commit, died inside it, and the whole gang strict-restored the
# previous step (fallback=False: the torn step never existed to fall
# back from)
env JAX_PLATFORMS=cpu python tools/postmortem.py --merge \
  "${DTF_ASYNCKILL_DUMPS:-artifacts/asynckill_dumps}"/fleet.jsonl \
  "${DTF_ASYNCKILL_DUMPS:-artifacts/asynckill_dumps}"/flightrec-w*.jsonl \
  --out "${DTF_ASYNCKILL_MERGED:-artifacts/asynckill_merged_postmortem.jsonl}" --quiet \
  --expect 'ckpt_async_begin,fault_fired[fault=async_commit_kill],ckpt_restore[fallback=False]' \
  --expect 'fleet_worker_dead,fleet_gang_stop,fleet_restart,fleet_done'
# p2p catch-up (ISSUE 18): the rejoin story on the merged timeline — a
# survivor exported an offer and the joiner imported it (each chain
# anchored on fleet-clock events; offer->import causality is enforced
# by the file protocol itself, rename-published offers cannot be
# imported before they exist)
env JAX_PLATFORMS=cpu python tools/postmortem.py --merge \
  "${DTF_P2P_DUMPS:-artifacts/p2p_dumps}"/fleet.jsonl \
  "${DTF_P2P_DUMPS:-artifacts/p2p_dumps}"/flightrec-w*.jsonl \
  --out "${DTF_P2P_MERGED:-artifacts/p2p_merged_postmortem.jsonl}" --quiet \
  --expect 'fleet_worker_dead,catchup_offer,fleet_done' \
  --expect 'fleet_worker_dead,catchup_restore[src=w1i1],fleet_rejoin,fleet_done'
# hierarchical fault domains (ISSUE 19): pod B's outage must read as a
# POD-local story on the merged timeline — outage, per-pod-quorum
# strict restore on BOTH pod-B workers, rejoin — while pod A never
# stops (the round itself asserts pod A's forward progress on the raw
# staged dumps; the absence of fleet_gang_stop here is the merged-view
# half of the same invariant)
env JAX_PLATFORMS=cpu python tools/postmortem.py --merge \
  "${DTF_POD_DUMPS:-artifacts/pod_dumps}"/fleet.jsonl \
  "${DTF_POD_DUMPS:-artifacts/pod_dumps}"/flightrec-p*.jsonl \
  --out "${DTF_POD_MERGED:-artifacts/pod_merged_postmortem.jsonl}" --quiet \
  --expect 'pod_outage[pod=1],pod_restart[pod=1],pod_rejoin[pod=1],fleet_done' \
  --expect 'pod_outage[pod=1],ckpt_restore[src=p1w0i2,fallback=False],pod_rejoin[pod=1],fleet_done' \
  --expect 'pod_outage[pod=1],ckpt_restore[src=p1w1i2,fallback=False],pod_rejoin[pod=1],fleet_done'
# partition tolerance (ISSUE 19): a severed control plane is FENCED,
# never restarted — one fence, one unfence, and the slow-beat pod is
# judged live (gray failure ≠ partition)
env JAX_PLATFORMS=cpu python tools/postmortem.py --merge \
  "${DTF_PARTITION_DUMPS:-artifacts/partition_dumps}"/fleet.jsonl \
  "${DTF_PARTITION_DUMPS:-artifacts/partition_dumps}"/flightrec-p*.jsonl \
  --out "${DTF_PARTITION_MERGED:-artifacts/partition_merged_postmortem.jsonl}" --quiet \
  --expect 'fault_fired[fault=control_plane_partition],pod_fence[pod=1],pod_unfence[pod=1],fleet_done' \
  --expect 'fault_fired[fault=slow_control_plane],fleet_done'
env JAX_PLATFORMS=cpu python tools/fleet_top.py --once \
  --fleet-dir "${DTF_FLEET_DUMPS:-artifacts/fleet_dumps}" >/dev/null
# keep the previous serve bench around as the bench_trend baseline,
# same freshest-pair scheme as the sweep sentinel above (ISSUE 20)
if [ -f artifacts/serve_chaos.json ]; then
  cp artifacts/serve_chaos.json artifacts/serve_chaos_prev.json
fi
env JAX_PLATFORMS=cpu python tools/bench_serve.py --preset chaos \
  --requests 10 --slots 4 --max-new 8 --parity-check \
  --spec-k 4 --compare-baseline --min-speedup 1.1 \
  --json artifacts/serve_chaos.json >/dev/null
# serve perf-regression sentinel (ISSUE 20): chaos tok/s on shared CI
# hosts is noisy, so the budget is generous — this catches collapses
# (a rollback bug serializing the verify step), not percent-level drift
if [ -f artifacts/serve_chaos_prev.json ]; then
  env JAX_PLATFORMS=cpu python tools/bench_trend.py \
    artifacts/serve_chaos_prev.json artifacts/serve_chaos.json \
    --metric tokens_per_sec --max-regress-pct 60
fi
# serve fleet (ISSUE 16): re-merge the serve-fleet failover round's
# per-process dumps (router/supervisor + surviving replicas, clocks
# aligned on the serve_route dispatch/ACK handshake) and gate the
# failover story: replica dead -> requeue at lane head -> a survivor
# admits the re-prefilled request -> fleet_done
env JAX_PLATFORMS=cpu python tools/postmortem.py --merge \
  "${DTF_SERVE_FLEET_DUMPS:-artifacts/serve_fleet_dumps}"/fleet.jsonl \
  "${DTF_SERVE_FLEET_DUMPS:-artifacts/serve_fleet_dumps}"/flightrec-w*.jsonl \
  --out "${DTF_SERVE_FLEET_MERGED:-artifacts/serve_fleet_merged_postmortem.jsonl}" --quiet \
  --expect 'serve_replica_dead,serve_requeue,serve_admit,fleet_done'
# request ledger (ISSUE 17): one killed request's merged trace must tell
# the WHOLE story across both replica processes on one aligned timeline,
# and every slow request's TTFT must decompose into named phases that
# sum to the measurement
env JAX_PLATFORMS=cpu python tools/trace_view.py \
  "${DTF_SERVE_FLEET_DUMPS:-artifacts/serve_fleet_dumps}"/reqtrace-router.jsonl \
  "${DTF_SERVE_FLEET_DUMPS:-artifacts/serve_fleet_dumps}"/reqtrace-w*.jsonl \
  --out "${DTF_SERVE_FLEET_TRACE:-artifacts/serve_fleet_trace_merged.jsonl}" \
  --slowest 3 \
  --expect 'queue_wait,route,admission_block,prefill_chunks,decode_gap,requeue_reprefill,route,admission_block,prefill_chunks,decode_gap,finish' \
  --require-replicas 2 >/dev/null
echo "ci_fast: all gates passed"
