#!/usr/bin/env python
"""CTR convergence demo: the full real-data Wide&Deep path, end to end —
teacher-labeled Criteo-FORMAT TSV -> tools/make_ctr_records.py converter
(hashing, log1p, record layout) -> `--data.dataset=ctr:` through the
native record loader -> wide_deep training (FTRL wide / AdaGrad deep) ->
held-out AUC from a separate converted file.

The corpus is synthetic but LEARNABLE (a fixed random teacher over the
hashed categorical ids + dense values labels the clicks), so AUC has
real headroom above 0.5 and the gate is meaningful: a broken hash,
misaligned record layout, or dead embedding gradient path all push AUC
back to ~0.5. The BASELINE.json:11 Wide&Deep config made concrete.

Usage: python tools/convergence_demo_ctr.py [--steps 300] [--min-auc 0.75]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# bench-tool platform discipline: honor an explicit JAX_PLATFORMS pin,
# probe the tunneled accelerator, fall back to CPU when the relay is
# down (a dead tunnel must not hang a convergence demo)
from distributed_tensorflow_tpu.utils.benchmarking import (  # noqa: E402
    fall_back_to_cpu_if_unreachable, honor_env_platform,
)

honor_env_platform()
fall_back_to_cpu_if_unreachable(log=lambda m: print(m, file=sys.stderr))

import jax  # noqa: E402

if jax.config.jax_platforms and "cpu" in str(jax.config.jax_platforms):
    # wide_deep's default mesh is embedding-parallel (model=2): give the
    # CPU rig 8 fake devices (before any backend init) so the demo
    # exercises the real sharded-table path like the test conftest does
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402

N_DENSE, N_CAT, VOCAB = 6, 4, 500


def write_teacher_tsv(path: str, n: int, seed: int) -> None:
    """Criteo-format lines whose labels come from a fixed teacher over
    the HASHED ids — exactly what the converter will reproduce — plus
    the dense values, so the mapping is learnable end to end."""
    from tools.make_ctr_records import hash_token

    r = np.random.RandomState(0)  # teacher fixed across train/eval
    tables = [r.randn(VOCAB) for _ in range(N_CAT)]
    w_dense = r.randn(N_DENSE) * 0.5

    r = np.random.RandomState(seed)  # examples differ per split
    rows = []
    scores = np.empty(n)
    for j in range(n):
        raw_dense = r.randint(0, 100, N_DENSE)
        toks = ["%06x" % r.randint(0, 16**6) for _ in range(N_CAT)]
        ids = [hash_token(t, VOCAB) for t in toks]
        scores[j] = (sum(tables[i][ids[i]] for i in range(N_CAT))
                     + float(np.log1p(raw_dense) @ w_dense))
        rows.append((raw_dense, toks))
    # threshold at the TEACHER's median (fixed from the train seed), not
    # 0: the dense term has an uncentered offset that would otherwise
    # collapse the labels to one class (and AUC to undefined)
    thresh = np.median(scores) if seed == 1 else write_teacher_tsv.thresh
    write_teacher_tsv.thresh = thresh
    with open(path, "w") as f:
        for (raw_dense, toks), sc in zip(rows, scores):
            label = int(sc > thresh)
            f.write("\t".join(
                [str(label)] + [str(v) for v in raw_dense] + toks) + "\n")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=600)
    ap.add_argument("--min-auc", type=float, default=0.75,
                    help="held-out AUC gate (chance = 0.5)")
    args = ap.parse_args()

    from distributed_tensorflow_tpu import workloads

    work = tempfile.mkdtemp(prefix="dtf_ctr_demo_")
    train_tsv = os.path.join(work, "train.txt")
    eval_tsv = os.path.join(work, "eval.txt")
    write_teacher_tsv(train_tsv, 6000, seed=1)
    write_teacher_tsv(eval_tsv, 1500, seed=2)

    for tsv, out in ((train_tsv, "train.dat"), (eval_tsv, "eval.dat")):
        subprocess.run(
            [sys.executable, os.path.join(REPO, "tools/make_ctr_records.py"),
             os.path.join(work, out), tsv,
             "--vocab-size", str(VOCAB), "--n-dense", str(N_DENSE)],
            check=True, capture_output=True,
        )

    vocabs = "[" + ",".join([str(VOCAB)] * N_CAT) + "]"
    common = [
        f"--model.vocab_sizes={vocabs}",
        f"--model.dense_features={N_DENSE}",
        "--model.embed_dim=8",
        "--model.hidden_sizes=[32,16]",
        "--data.global_batch_size=256",
        "--optimizer.learning_rate=0.08",
    ]
    ckdir = os.path.join(work, "ck")
    result = workloads.run_workload("wide_deep", [
        f"--data.dataset=ctr:{work}/train.dat",
        f"--train.num_steps={args.steps}",
        f"--train.log_every={min(50, args.steps)}",
        "--train.eval_batches=0",
        f"--checkpoint.directory={ckdir}",
        "--checkpoint.async_save=false",
        "--checkpoint.save_on_preemption=false",
        *common,
    ])

    eval_metrics = workloads.eval_workload("wide_deep", [
        # explicit held-out eval file => the unprefixed `auc` key (eval
        # drawn from data.dataset would be tagged train_auc)
        f"--data.eval_dataset=ctr:{work}/eval.dat",
        f"--data.dataset=ctr:{work}/train.dat",
        f"--checkpoint.directory={ckdir}",
        "--train.eval_batches=5",
        *common,
    ])
    auc = float(eval_metrics.get("auc", 0.0))
    print(json.dumps({
        "train_loss": round(float(result.history[-1]["loss"]), 4),
        "eval_auc": round(auc, 4),
        "steps": args.steps,
        "dataset": "teacher-labeled Criteo-format TSV via "
                   "make_ctr_records.py, 6000/1500 split",
    }))
    if auc < args.min_auc:
        raise SystemExit(f"held-out AUC {auc:.3f} < {args.min_auc} gate")


if __name__ == "__main__":
    main()
