#!/bin/bash
# Exclusive chip-session wrapper: run CMD holding the single-device-lease
# lock. Every python process that imports distributed_tensorflow_tpu (or
# runs pytest) while this lock is held pins itself to CPU — see
# distributed_tensorflow_tpu/utils/chip_lock.py for the protocol.
# Usage: bash tools/chip_session.sh CMD [ARGS...]
set -u
LOCK=${DTF_CHIP_LOCK:-/tmp/dtf_chip_session.lock}
exec 9>>"$LOCK.flock"
if ! flock -n 9; then
  echo "chip_session: another session already holds $LOCK.flock" >&2
  exit 97
fi
echo $$ >"$LOCK"
trap 'rm -f "$LOCK"' EXIT INT TERM
DTF_CHIP_SESSION=1 "$@"
