#!/bin/bash
# Exclusive chip-session wrapper: run CMD holding the single-device-lease
# lock. Every python process that imports distributed_tensorflow_tpu (or
# runs pytest) while this lock is held pins itself to CPU — see
# distributed_tensorflow_tpu/utils/chip_lock.py for the protocol.
#
# Bare-`import jax` scripts that never import the framework are outside
# that guard (there is no in-repo sitecustomize hook: site init imports
# the environment-owned /root/.axon_site/sitecustomize.py first). Two
# mitigations while the session runs:
#   - an env file at $LOCK.env exporting JAX_PLATFORMS=cpu, for any
#     shell to source before running ad-hoc python
#     (`source /tmp/dtf_chip_session.lock.env 2>/dev/null`)
#   - the protocol: relay probes go through tools/probe.py, which
#     refuses to touch the device while the flock is held.
# Usage: bash tools/chip_session.sh CMD [ARGS...]
set -u
LOCK=${DTF_CHIP_LOCK:-/tmp/dtf_chip_session.lock}
# Acquire-and-verify loop: a stale-lock checker (chip_lock._stale) may
# unlink the sidecar between our open and flock — we could then hold a
# lock on an UNLINKED inode while a later session locks a fresh one,
# breaking mutual exclusion. After locking, verify fd 9 still names the
# path (-ef compares device+inode); reopen on mismatch. The checker
# also holds the flock for the instant it unlinks, so one transient
# flock failure gets brief retries before reading as a live session.
got=
for attempt in 1 2 3 4 5; do
  exec 9>>"$LOCK.flock"
  if flock -n 9; then
    if [ "$LOCK.flock" -ef "/proc/$$/fd/9" ]; then got=1; break; fi
    # sidecar unlinked under us: reopen the fresh inode and re-lock
  else
    sleep 0.2
  fi
done
if [ -z "$got" ]; then
  echo "chip_session: another session already holds $LOCK.flock" >&2
  exit 97
fi
echo $$ >"$LOCK"
# MEASURED (round 5): JAX_PLATFORMS=cpu alone does NOT pin a bare-jax
# process here — the axon sitecustomize's register() overrides the
# env-derived config default, and backend init then dials the relay
# (hangs when it's down, contends when it's up). The effective pin for
# a fresh interpreter is disabling the bootstrap gate as well.
{
  echo '# chip session live; removed on exit'
  echo 'export JAX_PLATFORMS=cpu'
  echo 'unset PALLAS_AXON_POOL_IPS'
} >"$LOCK.env"
trap 'rm -f "$LOCK" "$LOCK.env"' EXIT INT TERM
DTF_CHIP_SESSION=1 "$@"
