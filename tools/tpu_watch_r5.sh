#!/bin/bash
# Round-5 relay watcher: probe the tunneled TPU every ~4 min via the
# canonical tools/probe.py (every verdict lands in the shared probe
# cache, so a driver-invoked bench.py reuses it instead of hanging on
# its own probe — VERDICT r4 items 1/3); at the first healthy window
# take the chip-session lock and fire the TIERED tools/onchip_round5.sh
# (<=25-min decisive prefix, then best-effort — VERDICT r4 item 2).
# Exits when a session has been captured (or the deadline passes) so
# the invoking shell gets control back.
# Usage: bash tools/tpu_watch_r5.sh [deadline_epoch_s]
set -u
cd "$(dirname "$0")/.."
DEADLINE=${1:-$(($(date +%s) + 11*3600))}
LOG=/tmp/tpu_watch_r5.log
echo "watcher start $(date -u +%F' '%T) deadline $(date -u -d @"$DEADLINE" +%T)" | tee -a "$LOG"

n=0
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  n=$((n+1))
  echo "--- probe $n $(date -u +%T)" >>"$LOG"
  # tools/probe.py: refuses to probe while a chip session is live (the
  # probe is a bare device init and would contend for the single
  # lease), retries one hang, and writes the shared cache either way.
  # 90 s budget: healthy init is 16-20 s measured, and the worst-case
  # outage cycle (2x90 hung + 240 sleep = 420 s) then exactly matches
  # the bench ladder's cache TTL — the driver always finds a fresh
  # verdict (utils/benchmarking.fall_back_to_cpu_if_unreachable).
  python -u tools/probe.py 90 >>"$LOG" 2>&1
  rc=$?
  if [ $rc -eq 0 ]; then
    echo "=== RELAY UP at probe $n ($(date -u +%T)); firing onchip_round5.sh ===" | tee -a "$LOG"
    bash tools/chip_session.sh bash tools/onchip_round5.sh /tmp/onchip_r5 \
      >>"$LOG" 2>&1
    rc=$?
    echo "=== session rc=$rc ($(date -u +%T)) ===" | tee -a "$LOG"
    # commit the evidence immediately: only committed files survive a
    # round end, and the session may land with no builder turns left.
    # (The session script already commits per-tier; this catches any
    # tail files. Pathspec-restricted: must not sweep unrelated staged
    # work into the auto-commit — ADVICE r4.)
    git add artifacts/onchip_r5 >>"$LOG" 2>&1
    git commit -m "Round-5 on-chip session artifacts (auto-committed by the relay watcher)" \
      -- artifacts/onchip_r5 >>"$LOG" 2>&1 \
      || echo "watcher: nothing left to commit" >>"$LOG"
    # a COMPLETE session retires the watcher; an incomplete one (probe
    # flapped at start, or the mid-session dead-relay abort) re-arms —
    # a later window can re-run the queue (r3's window was 41 min; the
    # outage pattern allows another)
    [ $rc -eq 0 ] && exit 0
    echo "session incomplete (rc=$rc); re-arming" | tee -a "$LOG"
  fi
  sleep 240
done
echo "watcher deadline passed without a healthy window" | tee -a "$LOG"
exit 99
