#!/usr/bin/env python
"""Render a flight-recorder postmortem dump as a causal timeline.

The dump (obs/flightrec.py: one JSON header line + one JSON event per
line, monotonic timestamps) is written by the train loop on an unhandled
step exception, by the Supervisor on ``SupervisorExhausted``, or on
request (``tests/chaos_worker.py --flightrec``). This tool answers the
operator question the raw JSONL can't: *what happened, in what order,
and what did recovery do about it* — e.g.

    t+0.412s  fault_fired          step=3   fault=sigterm
    t+0.498s  ckpt_save            step=4   trigger=preemption
    t+0.501s  train_stop           step=4   reason=preempted; ...
    t+0.502s  sup_restart                   restart=1 cause=preemption
    t+0.607s  fault_fired          step=4   fault=ckpt_corrupt restart=1
    t+0.633s  ckpt_quarantine      step=4   note=...
    t+0.671s  ckpt_restore         step=2   fallback=True

Validation (exit 1 on failure, the CI gate in tools/ci_fast.sh):

- schema: header tag, per-event required keys, known event kinds,
  non-decreasing timestamps (``obs.flightrec.validate_dump``);
- ordering: ``--expect k1,k2[attr=v],...`` asserts the timeline contains
  those events as a causal subsequence (``obs.flightrec.contains_in_order``).

Usage:
    python tools/postmortem.py <dump.jsonl>
    python tools/postmortem.py <dump.jsonl> --expect \
        'fault_fired[fault=sigterm],ckpt_save[trigger=preemption],sup_restart'
"""

import argparse
import json
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

#: step_start/step_end floods are collapsed into one summary line when a
#: run of them is at least this long
COLLAPSE_RUN = 5
_STEP_KINDS = ("step_start", "step_end")


def load(path):
    """Returns (header_dict, [event_dict, ...])."""
    with open(path) as f:
        lines = f.read().splitlines()
    if not lines:
        raise ValueError(f"empty dump: {path}")
    header = json.loads(lines[0])
    events = [json.loads(line) for line in lines[1:]]
    return header, events


def parse_expect(spec: str):
    """``kind`` or ``kind[attr=v,attr2=v2]`` items, comma-separated at
    the top level only."""
    specs = []
    for item in filter(None, (s.strip() for s in _split_top(spec))):
        if "[" in item:
            kind, _, rest = item.partition("[")
            if not rest.endswith("]"):
                raise ValueError(f"bad expect item {item!r}")
            attrs = {}
            for pair in rest[:-1].split(","):
                k, _, v = pair.partition("=")
                if not k or not _:
                    raise ValueError(f"bad expect attr {pair!r} in {item!r}")
                attrs[k.strip()] = v.strip()
            specs.append((kind.strip(), attrs))
        else:
            specs.append((item, {}))
    return specs


def _split_top(spec: str):
    """Split on commas not inside [...] brackets."""
    out, buf, depth = [], [], 0
    for ch in spec:
        if ch == "[":
            depth += 1
        elif ch == "]":
            depth -= 1
        if ch == "," and depth == 0:
            out.append("".join(buf))
            buf = []
        else:
            buf.append(ch)
    out.append("".join(buf))
    return out


def _fmt_event(e, t0):
    attrs = {k: v for k, v in e.items() if k not in ("t", "kind", "step")}
    step = f"step={e['step']:<6}" if "step" in e else " " * 11
    body = " ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
    return f"  t+{e['t'] - t0:9.3f}s  {e['kind']:<20} {step} {body}".rstrip()


def render(header, events, out=sys.stdout):
    """Human timeline; consecutive step_start/step_end runs collapsed."""
    t0 = events[0]["t"] if events else header.get("dumped_t", 0.0)
    span = events[-1]["t"] - t0 if events else 0.0
    print(
        f"FLIGHT RECORDER POSTMORTEM  reason={header.get('reason') or '-'}  "
        f"{len(events)} events ({header.get('dropped', 0)} dropped, "
        f"ring capacity {header.get('capacity')})  span {span:.3f}s  "
        f"pid {header.get('pid')}",
        file=out,
    )
    i = 0
    while i < len(events):
        e = events[i]
        if e["kind"] in _STEP_KINDS:
            j = i
            while j < len(events) and events[j]["kind"] in _STEP_KINDS:
                j += 1
            if j - i >= COLLAPSE_RUN:
                steps = [ev.get("step") for ev in events[i:j]
                         if ev.get("step") is not None]
                span_lbl = (f"steps {min(steps)}–{max(steps)}" if steps
                            else "no step ids")  # step is optional
                print(
                    f"  t+{e['t'] - t0:9.3f}s  … {j - i} step events "
                    f"({span_lbl}) over "
                    f"{events[j - 1]['t'] - e['t']:.3f}s …",
                    file=out,
                )
                i = j
                continue
        print(_fmt_event(e, t0), file=out)
        i += 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("dump", help="postmortem JSONL written by the recorder")
    ap.add_argument("--expect", default=None,
                    help="comma-separated 'kind' or 'kind[attr=val,...]' "
                         "items that must appear in this causal order")
    ap.add_argument("--quiet", action="store_true",
                    help="validate only; skip the rendered timeline")
    args = ap.parse_args(argv)

    from distributed_tensorflow_tpu.obs import flightrec as fr

    failures = fr.validate_dump(args.dump)
    header, events = ({}, [])
    if not failures:
        header, events = load(args.dump)
        if not args.quiet:
            render(header, events)
    if args.expect and not failures:
        specs = parse_expect(args.expect)
        if not fr.contains_in_order(events, specs):
            failures.append(
                f"timeline does not contain the expected causal sequence: "
                f"{args.expect}"
            )
    for f in failures:
        print(f"FAIL: {f}", file=sys.stderr)
    if not failures:
        print(f"OK: {args.dump} valid ({len(events)} events"
              + (f", causal order '{args.expect}' present" if args.expect
                 else "") + ")",
              file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
