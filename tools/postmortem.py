#!/usr/bin/env python
"""Render flight-recorder postmortem dumps as causal timelines —
single-process, or MERGED across a fleet.

A single dump (obs/flightrec.py: one JSON header line + one JSON event
per line, monotonic timestamps) is written by the train loop on an
unhandled step exception, by the Supervisor on ``SupervisorExhausted``,
or on request (``tests/chaos_worker.py --flightrec``). This tool answers
the operator question the raw JSONL can't: *what happened, in what
order, and what did recovery do about it* — e.g.

    t+0.412s  fault_fired          step=3   fault=sigterm
    t+0.498s  ckpt_save            step=4   trigger=preemption
    t+0.501s  train_stop           step=4   reason=preempted; ...
    t+0.502s  sup_restart                   restart=1 cause=preemption
    t+0.607s  fault_fired          step=4   fault=ckpt_corrupt restart=1
    t+0.633s  ckpt_quarantine      step=4   note=...
    t+0.671s  ckpt_restore         step=2   fallback=True

With ``--merge``, the FIRST dump is the fleet supervisor's and the rest
are per-worker dumps (headers stamped ``worker``/``incarnation``); the
tool aligns their incomparable per-process monotonic clocks on shared
control-plane anchors (``obs/fleetview.merge_timelines``: launches,
snapshot merges, relayed restores, the resize handshake), renders ONE
pod-scale timeline with a ``src`` column, optionally writes it
(``--out``, schema ``dtf-fleetmerge-1``), and applies every ``--expect``
to the merged sequence — a CROSS-PROCESS causal gate ("the gang stop
precedes every worker's restore"). A dump whose header already carries
``dtf-fleetmerge-1`` is validated as a merged timeline.

Validation (exit 1 on failure, the CI gates in tools/ci_fast.sh):

- schema: header tag, per-event required keys, known event kinds,
  non-decreasing timestamps (``obs.flightrec.validate_dump`` /
  ``obs.fleetview.validate_merged_dump``);
- anchors (merge mode): a worker dump with no launch anchor, ambiguous
  anchors, inconsistent offset bounds, or a worker label collision
  fails the merge;
- ordering: each ``--expect k1,k2[attr=v],...`` (repeatable) asserts
  the timeline contains those events as a causal subsequence
  (``obs.flightrec.contains_in_order``; merged events carry
  ``src=fleet|w<i>i<k>`` for per-process pinning).

Usage:
    python tools/postmortem.py <dump.jsonl> [--expect ...]
    python tools/postmortem.py --merge <fleet.jsonl> <worker.jsonl>... \
        --out merged.jsonl --expect 'fleet_gang_stop,ckpt_restore[src=w0i2]'
"""

import argparse
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

#: step_start/step_end floods are collapsed into one summary line when a
#: run of them is at least this long
COLLAPSE_RUN = 5
_STEP_KINDS = ("step_start", "step_end")


def load(path):
    """Returns (header_dict, [event_dict, ...]) — the one JSONL-dump
    reader, shared with the merge library."""
    from distributed_tensorflow_tpu.obs import fleetview as fv

    return fv.load_dump(path)


def parse_expect(spec: str):
    """``kind`` or ``kind[attr=v,attr2=v2]`` items, comma-separated at
    the top level only."""
    specs = []
    for item in filter(None, (s.strip() for s in _split_top(spec))):
        if "[" in item:
            kind, _, rest = item.partition("[")
            if not rest.endswith("]"):
                raise ValueError(f"bad expect item {item!r}")
            attrs = {}
            for pair in rest[:-1].split(","):
                k, _, v = pair.partition("=")
                if not k or not _:
                    raise ValueError(f"bad expect attr {pair!r} in {item!r}")
                attrs[k.strip()] = v.strip()
            specs.append((kind.strip(), attrs))
        else:
            specs.append((item, {}))
    return specs


def _split_top(spec: str):
    """Split on commas not inside [...] brackets."""
    out, buf, depth = [], [], 0
    for ch in spec:
        if ch == "[":
            depth += 1
        elif ch == "]":
            depth -= 1
        if ch == "," and depth == 0:
            out.append("".join(buf))
            buf = []
        else:
            buf.append(ch)
    out.append("".join(buf))
    return out


def _fmt_event(e, t0, with_src=False):
    skip = ("t", "kind", "step", "src") if with_src else ("t", "kind", "step")
    attrs = {k: v for k, v in e.items() if k not in skip}
    step = f"step={e['step']:<6}" if "step" in e else " " * 11
    src = f"{e.get('src', ''):<8}" if with_src else ""
    body = " ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
    return (f"  t+{e['t'] - t0:9.3f}s  {src}{e['kind']:<20} "
            f"{step} {body}").rstrip()


def render(header, events, out=sys.stdout, with_src=False):
    """Human timeline; consecutive step_start/step_end runs collapsed."""
    t0 = events[0]["t"] if events else header.get("dumped_t", 0.0)
    span = events[-1]["t"] - t0 if events else 0.0
    if with_src:
        srcs = [s.get("src") for s in header.get("sources", [])]
        print(
            f"MERGED FLEET POSTMORTEM  reason={header.get('reason') or '-'}  "
            f"{len(events)} events from {len(srcs)} processes "
            f"({', '.join(map(str, srcs))})  span {span:.3f}s",
            file=out,
        )
    else:
        print(
            f"FLIGHT RECORDER POSTMORTEM  reason={header.get('reason') or '-'}"
            f"  {len(events)} events ({header.get('dropped', 0)} dropped, "
            f"ring capacity {header.get('capacity')})  span {span:.3f}s  "
            f"pid {header.get('pid')}",
            file=out,
        )
    i = 0
    while i < len(events):
        e = events[i]
        if e["kind"] in _STEP_KINDS:
            j = i
            while j < len(events) and events[j]["kind"] in _STEP_KINDS:
                j += 1
            if j - i >= COLLAPSE_RUN:
                steps = [ev.get("step") for ev in events[i:j]
                         if ev.get("step") is not None]
                span_lbl = (f"steps {min(steps)}–{max(steps)}" if steps
                            else "no step ids")  # step is optional
                print(
                    f"  t+{e['t'] - t0:9.3f}s  … {j - i} step events "
                    f"({span_lbl}) over "
                    f"{events[j - 1]['t'] - e['t']:.3f}s …",
                    file=out,
                )
                i = j
                continue
        print(_fmt_event(e, t0, with_src=with_src), file=out)
        i += 1


def _check_expects(events, expects, failures) -> None:
    from distributed_tensorflow_tpu.obs import flightrec as fr

    for spec in expects or []:
        if not fr.contains_in_order(events, parse_expect(spec)):
            failures.append(
                f"timeline does not contain the expected causal "
                f"sequence: {spec}")


def _run_merge(args) -> int:
    from distributed_tensorflow_tpu.obs import fleetview as fv

    if len(args.dump) < 2:
        print("FAIL: --merge needs a fleet dump plus at least one "
              "worker dump", file=sys.stderr)
        return 1
    header, events, failures = fv.merge_timelines(
        args.dump[0], args.dump[1:], reason="postmortem --merge")
    if not failures and args.out:
        fv.write_merged(args.out, header, events)
        failures += fv.validate_merged_dump(args.out)
    if not failures and not args.quiet:
        render(header, events, with_src=True)
    if not failures:
        _check_expects(events, args.expect, failures)
    for f in failures:
        print(f"FAIL: {f}", file=sys.stderr)
    if not failures:
        print(f"OK: merged {len(args.dump)} dumps into {len(events)} "
              f"events" + (f" -> {args.out}" if args.out else "")
              + (f"; causal order present for {len(args.expect)} "
                 f"expectation(s)" if args.expect else ""),
              file=sys.stderr)
    return 1 if failures else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("dump", nargs="+",
                    help="postmortem JSONL dump(s); with --merge the "
                         "first is the fleet's and the rest are workers'")
    ap.add_argument("--merge", action="store_true",
                    help="align the dumps' clocks on control-plane "
                         "anchors and gate ONE merged cross-worker "
                         "timeline")
    ap.add_argument("--out", default=None,
                    help="with --merge: write the merged timeline "
                         "(dtf-fleetmerge-1 JSONL) here")
    ap.add_argument("--expect", action="append", default=None,
                    help="comma-separated 'kind' or 'kind[attr=val,...]' "
                         "items that must appear in this causal order "
                         "(repeatable; each spec is checked separately)")
    ap.add_argument("--quiet", action="store_true",
                    help="validate only; skip the rendered timeline")
    args = ap.parse_args(argv)

    if args.merge:
        return _run_merge(args)
    if len(args.dump) != 1:
        print("FAIL: multiple dumps require --merge", file=sys.stderr)
        return 1
    path = args.dump[0]

    from distributed_tensorflow_tpu.obs import fleetview as fv
    from distributed_tensorflow_tpu.obs import flightrec as fr

    try:
        header, events = load(path)
    except (OSError, ValueError) as e:
        print(f"FAIL: unreadable dump: {e}", file=sys.stderr)
        return 1
    merged = header.get("schema") == fv.MERGED_SCHEMA
    failures = (fv.validate_merged_dump(path) if merged
                else fr.validate_dump(path))
    if not failures and not args.quiet:
        render(header, events, with_src=merged)
    if not failures:
        _check_expects(events, args.expect, failures)
    for f in failures:
        print(f"FAIL: {f}", file=sys.stderr)
    if not failures:
        n = len(args.expect) if args.expect else 0
        print(f"OK: {path} valid ({len(events)} events"
              + (f", causal order present for {n} expectation(s)" if n
                 else "") + ")",
              file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
