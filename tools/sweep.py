#!/usr/bin/env python
"""Scaling observatory: the measured mesh-config × workload sweep.

ROADMAP item 4 / the MLPerf-0.6 TPU-pod recipe (arXiv:1909.09756): we
had the parallelism knobs (the MULTICHIP dryruns exercise dp / fsdp /
tp / sp / ep / pp / hybrid on 8 CPU devices) and the meters
(obs/goodput's single MFU site, the goodput ledger) but no measured
curves connecting them. This harness runs the matrix and produces them:

- one CELL per (mesh config, workload): a short Trainer run on that
  mesh over a device subset, steps/sec and examples/sec from the
  steady-state ``train_step_seconds`` histogram (first step — compile —
  excluded), per-cell goodput fraction from the ledger counters, MFU
  through ``obs/goodput.train_mfu`` (THE multiplier site; dtflint pins
  it) — all isolated per cell with ``Registry.delta`` snapshots, never
  a mid-run ``reset()``;
- a distributed-eval pass per cell (train/evaluation.py: batch sharded
  over the mesh, host-side fixed-order reduction) so the eval surface
  is exercised on every mesh shape the sweep claims works;
- a schema-versioned ``dtf-scaling-1`` report (obs/scaling.py) where
  EVERY cell is provenance-stamped (backend, device kind/count, mesh
  shape, git sha, hostname) — after BENCH_r02–r05 silently recorded
  CPU fallbacks as if they were TPU rows, no number leaves this tool
  without its platform context;
- per-axis scaling efficiency vs the 1-device baseline and an enforced
  gate: 8-dev dp must hold ≥ 0.8 × ideal. On the host-shared CPU rig
  the ideal is flat throughput (8 fake devices partition ONE host's
  silicon — the gate then bounds partitioning overhead); on real
  accelerators it is N × 1-dev (see obs/scaling.scaling_efficiency).

Exit codes: 0 ok · 2 usage · 3 scaling gate failed · 4 provenance
platform differs from --expect-platform (the masquerade tripwire).

Usage (the 8-device CPU rig):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        python tools/sweep.py --out artifacts/scaling.json
    python tools/sweep.py --dryrun --out /tmp/scaling.json   # 2-cell CI gate
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])


#: mesh cells — the MULTICHIP dryrun matrix as named sweep points:
#: name -> (devices needed, MeshSpec kwargs, scaling axis label)
MESH_CELLS = {
    "1dev":          (1, dict(data=1), "dp"),
    "dp2":           (2, dict(data=2), "dp"),
    "dp8":           (8, dict(data=8), "dp"),
    "dp4_tp2":       (8, dict(data=4, model=2), "tp"),
    "dp2_fsdp2_tp2": (8, dict(data=2, fsdp=2, model=2), "fsdp"),
    "dp8_hybrid2":   (8, dict(data=8, dcn_data=2), "hybrid"),
    # two-level fault-domain cells (parallel/mesh.PodTopology): the
    # pod boundary IS the DCN boundary, so the simulated two-pod mesh
    # is the hybrid recipe with the slice reinterpreted as the fault
    # domain resilience/podfleet.py supervises (ISSUE 19)
    "pod2_dp2":      (4, dict(num_pods=2, pod=dict(data=2)), "pod"),
    "pod2_dp2_tp2":  (8, dict(num_pods=2, pod=dict(data=2, model=2)),
                      "pod"),
}

#: sweep workloads: name -> (registry workload, default per-shard batch)
SWEEP_WORKLOADS = {
    "mlp": ("mnist_mlp", 128),
    "gpt": ("gpt_lm", 16),
}

DRYRUN_CELLS = ("1dev", "dp8", "pod2_dp2")


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _tiny_config(sweep_name: str, global_batch: int):
    """The workload's default config shrunk to sweep scale: the matrix
    measures parallelism overheads, not model quality, so models are
    small enough that a cell is seconds — but still the REAL workload
    builders, optimizers, and data paths."""
    from distributed_tensorflow_tpu import workloads

    workload, _ = SWEEP_WORKLOADS[sweep_name]
    mod = workloads.get(workload)
    cfg = mod.default_config()
    if sweep_name == "mlp":
        model = dataclasses.replace(cfg.model, hidden_sizes=(64, 64))
        data = dataclasses.replace(cfg.data, global_batch_size=global_batch)
    else:  # gpt: 2-layer toy decoder at seq 32
        model = dataclasses.replace(
            cfg.model, vocab_size=256, max_len=32, num_layers=2,
            d_model=32, num_heads=4, d_ff=64, dropout=0.0, xent_chunk=0)
        data = dataclasses.replace(
            cfg.data, global_batch_size=global_batch, seq_len=32,
            vocab_size=256)
    return dataclasses.replace(cfg, model=model, data=data), mod


def run_cell(sweep_name: str, cell_name: str, steps: int,
             per_shard_batch: int, eval_batches: int, seed: int,
             registry) -> dict:
    """Measure one (mesh, workload) cell. Returns the report cell dict."""
    import jax
    import numpy as np

    from distributed_tensorflow_tpu.obs import goodput, scaling
    from distributed_tensorflow_tpu.parallel import MeshSpec, build_mesh, describe
    from distributed_tensorflow_tpu.train import (
        ShardedEvaluator, StepOptions, Trainer, callbacks as cb,
        derive_metrics, init_train_state, make_optimizer, make_train_step,
    )
    from distributed_tensorflow_tpu.train.evaluation import batch_shards
    from distributed_tensorflow_tpu.utils import flops as flops_lib

    n_devices, spec_kw, axis = MESH_CELLS[cell_name]
    devices = jax.devices()[:n_devices]
    topo = None
    if "num_pods" in spec_kw:
        from distributed_tensorflow_tpu.parallel import PodTopology

        topo = PodTopology.from_dict(spec_kw).resolve(n_devices)
        spec = topo.to_mesh_spec().resolve(n_devices)
        log(f"cell {sweep_name}×{cell_name}: two-level {topo.describe()}")
    else:
        spec = MeshSpec(**spec_kw).resolve(n_devices)
    shards = spec.data * spec.fsdp
    global_batch = per_shard_batch * shards
    cfg, mod = _tiny_config(sweep_name, global_batch)
    mesh = build_mesh(spec, devices)
    log(f"cell {sweep_name}×{cell_name}: {describe(mesh)} "
        f"global_batch={global_batch}")

    parts = mod.build(cfg, mesh)
    tx = parts.tx if parts.tx is not None else make_optimizer(cfg.optimizer)
    state, specs = init_train_state(
        parts.init_fn, tx, mesh, jax.random.PRNGKey(seed),
        param_rules=parts.param_rules, param_specs=parts.param_specs,
        fsdp=parts.fsdp,
    )
    step_fn = make_train_step(parts.loss_fn, tx, StepOptions())

    baseline = registry.snapshot()
    # per-step latency + goodput booking only (every_n past the run:
    # the cadence'd gauge fetch never fires inside the measured window)
    telemetry = cb.TelemetryCallback(registry=registry, every_n=10**9)
    trainer = Trainer(step_fn, state, mesh, specs, callbacks=[telemetry])
    state = trainer.fit(parts.dataset_fn(0), num_steps=steps)
    delta = registry.delta(baseline)

    hist = delta.get("train_step_seconds")
    if not hist or not hist["sum"]:
        raise RuntimeError(
            f"cell {sweep_name}×{cell_name}: no steady-state step "
            f"observations (steps={steps} too small?)")
    steps_per_sec = hist["count"] / hist["sum"]
    productive = delta.get("goodput_productive_seconds_total",
                           {}).get("value", 0.0)
    wasted = sum(v["value"] for k, v in delta.items()
                 if k.startswith("wasted_seconds_total"))
    cell = {
        "cell": cell_name,
        "workload": sweep_name,
        "axis": axis,
        "n_devices": n_devices,
        "mesh": {a: int(mesh.shape[a]) for a in mesh.axis_names},
        "global_batch": global_batch,
        "steps": steps,
        "steps_per_sec": round(steps_per_sec, 3),
        "examples_per_sec": round(steps_per_sec * global_batch, 1),
        "goodput_fraction": round(productive / (productive + wasted), 4)
        if productive + wasted > 0 else None,
        "provenance": scaling.provenance(mesh),
    }
    if topo is not None:
        cell["pods"] = topo.num_pods
        cell["devices_per_pod"] = topo.devices_per_pod
    if parts.flops_per_step:
        # fwd-only count; the shared site applies the fwd+bwd multiplier
        cell["mfu"] = round(goodput.train_mfu(
            parts.flops_per_step, steps_per_sec, n_chips=n_devices,
            peak_per_chip=flops_lib.peak_flops_per_chip(devices[0]),
            registry=registry,
        ), 6)
    if eval_batches and parts.eval_fn is not None \
            and parts.eval_dataset_fn is not None:
        evaluator = ShardedEvaluator(parts.eval_fn, mesh, registry=registry)
        totals = evaluator.run(
            state, parts.eval_dataset_fn(eval_batches), eval_batches,
            step=int(np.asarray(state.step)))
        metrics = derive_metrics(totals, parts.eval_metric_prefix)
        if "loss" in metrics:
            cell["eval_loss"] = round(metrics["loss"], 6)
        cell["eval_batches"] = eval_batches
        cell["eval_shards"] = batch_shards(mesh)
    scaling.note_cell(registry)
    log(f"  steps/sec={cell['steps_per_sec']} "
        f"examples/sec={cell['examples_per_sec']} "
        f"mfu={cell.get('mfu')} goodput={cell['goodput_fraction']}")
    jax.clear_caches()  # free the cell's executables before the next one
    return cell


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--cells", default=None,
                    help=f"comma list from {sorted(MESH_CELLS)} "
                         f"(default: all)")
    ap.add_argument("--workloads", default=None,
                    help=f"comma list from {sorted(SWEEP_WORKLOADS)} "
                         f"(default: all)")
    ap.add_argument("--steps", type=int, default=12,
                    help="train steps per cell (first = compile, excluded)")
    ap.add_argument("--per-shard-batch", type=int, default=0,
                    help="examples per batch shard (0 = workload default)")
    ap.add_argument("--eval-batches", type=int, default=2,
                    help="distributed-eval batches per cell (0 disables)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--gate-dp", type=float, default=0.8,
                    help="min 8-dev dp scaling efficiency (0 disables)")
    ap.add_argument("--expect-platform", default="",
                    help="fail (rc 4) unless the measured provenance "
                         "platform is exactly this (CI masquerade tripwire)")
    ap.add_argument("--out", default="",
                    help="also write the report JSON here (atomic)")
    ap.add_argument("--dryrun", action="store_true",
                    help=f"CI mode: mlp × {DRYRUN_CELLS}, 8 steps")
    args = ap.parse_args(argv)

    from distributed_tensorflow_tpu.utils import benchmarking as bm

    bm.honor_env_platform()
    import jax

    from distributed_tensorflow_tpu.obs import scaling
    from distributed_tensorflow_tpu.obs.registry import default_registry

    if args.dryrun:
        if args.cells is not None or args.workloads is not None:
            # fixed matrix — silently ignoring an explicit selection
            # would measure the wrong cells and be trusted anyway
            ap.error("--dryrun fixes the matrix to "
                     f"mlp × {DRYRUN_CELLS}; drop --cells/--workloads")
        cells = list(DRYRUN_CELLS)
        workload_names = ["mlp"]
        args.steps = min(args.steps, 8)
    else:
        cells = [c.strip() for c in
                 (args.cells or ",".join(MESH_CELLS)).split(",")
                 if c.strip()]
        workload_names = [w.strip() for w in
                          (args.workloads or ",".join(SWEEP_WORKLOADS))
                          .split(",") if w.strip()]
    unknown = [c for c in cells if c not in MESH_CELLS] + \
        [w for w in workload_names if w not in SWEEP_WORKLOADS]
    if unknown:
        ap.error(f"unknown cells/workloads: {unknown}")

    n_available = jax.device_count()
    registry = default_registry()
    report_cells, skipped = [], []
    for sweep_name in workload_names:
        per_shard = args.per_shard_batch or SWEEP_WORKLOADS[sweep_name][1]
        for cell_name in cells:
            need = MESH_CELLS[cell_name][0]
            if need > n_available:
                # no silent caps: an absent cell is reported, not elided
                skipped.append({"cell": cell_name, "workload": sweep_name,
                                "reason": f"needs {need} devices, "
                                          f"have {n_available}"})
                log(f"cell {sweep_name}×{cell_name} SKIPPED: needs {need} "
                    f"devices, have {n_available}")
                continue
            report_cells.append(run_cell(
                sweep_name, cell_name, args.steps, per_shard,
                args.eval_batches, args.seed, registry))

    efficiency = scaling.scaling_efficiency(report_cells, registry)
    gates = []
    if args.gate_dp > 0:
        for e in efficiency:
            if e["axis"] == "dp" and e["n_devices"] == 8:
                gates.append({
                    "gate": f"{e['workload']}/{e['cell']}",
                    "axis": "dp",
                    "basis": e["basis"],
                    "threshold": args.gate_dp,
                    "value": e["value"],
                    "passed": e["value"] >= args.gate_dp,
                })
        if not gates:
            log("gate-dp: no 8-dev dp cell with a 1-dev baseline in this "
                "sweep; gate not evaluated")

    report = scaling.make_report(
        report_cells, efficiency, gates,
        extra={"skipped_cells": skipped, "steps_per_cell": args.steps},
    )
    if args.out:
        scaling.write_report(args.out, report)
        log(f"report -> {args.out}")
    else:
        failures = scaling.validate_scaling_report(report)
        if failures:
            raise ValueError("invalid scaling report:\n  "
                             + "\n  ".join(failures))
    print(json.dumps(report, indent=2, sort_keys=True))

    platform = report["provenance"]["platform"]
    if args.expect_platform and platform != args.expect_platform:
        log(f"FAIL: measured platform {platform!r} != expected "
            f"{args.expect_platform!r} — refusing to let this report "
            f"masquerade")
        return 4
    failed = [g for g in gates if not g["passed"]]
    if failed:
        log(f"FAIL: scaling gate(s) below threshold: {failed}")
        return 3
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
