#!/usr/bin/env python
"""Fast chaos smoke — the resilience gates quick enough for tools/ci_fast.sh.

Nine stages (full coverage lives in tests/test_resilience.py,
tests/test_supervisor.py, tests/test_anomaly.py, tests/test_fleet.py
and tests/test_serve.py; this is the canary that the recovery
machinery is wired at all):

1. **Scheduler admission invariants** (pure host, no device work):
   bounded-queue backpressure raises QueueFull, deadlines evict with
   FINISH_TIMEOUT from queue AND slot, cancel is idempotent, close()
   stops admission — driven on a FaultClock so it runs in microseconds.
2. **One SIGTERM→resume round** (two tests/chaos_worker.py
   subprocesses): a tiny train run SIGTERMs itself mid-run, exits via
   the coordinated preemption save, and a fresh process restores and
   finishes at the target step.
3. **One supervised recovery round** (one chaos_worker subprocess,
   --supervise): SIGTERM *and* a truncated-newest-checkpoint in the same
   run — the in-process Supervisor restarts, fallback restore
   quarantines the corrupt step and lands on an older valid one, and the
   run must still finish at the target step with finite params.
4. **One nan-blame round** (one chaos_worker subprocess, --supervise
   --anomaly): a recurring NaN batch at a fixed index plus a SIGTERM —
   the in-graph guard no-ops the poisoned step, the AnomalyPolicy skips
   it under budget and quarantines the exact (seed, index), and the
   preemption restart replays AROUND the hole to the target step with
   finite params and zero refused saves.
5. **One fleet gang-restart round** (resilience/fleet.py over two
   chaos_worker --fleet subprocesses): worker 1 hangs mid-run, the
   FleetSupervisor detects the death by MISSED HEARTBEATS (the process
   is still alive), SIGTERM/SIGKILLs the gang, bumps the incarnation,
   and relaunches from the latest common valid checkpoint — both
   workers must finish at the target step after exactly one restart.
   Its full outage window lands in `wasted_seconds_total{
   restart_recovery}` — the baseline the elastic round is measured
   against.
6. **One elastic shrink/rejoin round** (three chaos_worker --fleet
   --elastic subprocesses): worker 1 hard-dies at step 3 (os._exit, no
   save), the ELASTIC fleet holds the survivors at a barrier, reshards
   to world 2, relaunches the slot, and the replacement rejoins at the
   next barrier — zero gang restarts, with `restart_recovery` at least
   10x below the gang-restart baseline (ISSUE 12 acceptance).
7. **One p2p catch-up rejoin round** (the same three-worker elastic
   death as stage 6, run twice in one process): first WITHOUT
   --p2p-catchup as the replay baseline, then WITH it — the replacement
   requests the newest common valid checkpoint from a live survivor
   over the file control plane (claim-by-rename, export re-verified,
   offer rename-published, incarnation-fenced) instead of replaying.
   Gates: catchup_restore fired and catchup_fallback did not, rejoin
   wall (fleet_launch[rejoin] → fleet_done on the fleet clock) beats
   the replay baseline measured in the SAME run, and every worker's
   final params are bit-identical to an uninterrupted same-seed
   single-process run (ISSUE 18 acceptance).
8. **One async-commit-kill round** (two chaos_worker --fleet
   --async-save --strict-restore subprocesses): worker 1 is SIGKILLed
   INSIDE the background commit window of its step-4 async save
   (AsyncCommitKill fires at the shards_done seam). The torn step must
   be invisible — no `.corrupt` quarantine, no `.pending` residue, the
   fleet restore ceiling lands on the last PUBLISHED step — and the
   gang strict-restores it with fallback=False: nothing to fall back
   from, because the manifest-last commit order means the torn step
   never existed (ISSUE 18 acceptance).
9. **One serve-fleet failover round** (two serve/replica.py
   subprocesses under ServeFleetSupervisor): one replica is SIGKILLed
   mid-stream, its in-flight requests requeue at their lane heads and
   re-prefill on the survivor — every stream finishes, the survivor's
   drain audit is leak-free, and the corpse (by design) never writes
   one (ISSUE 16 acceptance).
10. **One two-pod outage round** (resilience/podfleet.py over 2 pods
    × 2 chaos_worker --pod subprocesses): pod B SIGKILLs itself
    mid-run (PodOutage), its pod supervisor gang-restarts ONLY pod B
    from pod B's own per-pod quorum ceiling with fallback=False,
    while pod A keeps recording strictly-increasing ``step_end``
    events right through the outage window — and every worker's final
    params are bit-identical to an uninterrupted same-seed straight
    run (ISSUE 19 acceptance).
11. **One control-plane partition round** (2 pods × 1 worker): pod
    B's worker heartbeat writes are redirected into a shadow file for
    a window longer than the heartbeat timeout (the process itself
    keeps training) while pod A's beats are merely SLOW — the pod
    supervisor must fence (pod_fence → pod_unfence, zero restarts,
    no split-brain relaunch double-training the batch range) and the
    slow pod must be judged LIVE (ISSUE 19 acceptance).

The fleet, elastic, p2p, async-kill, pod and partition rounds
additionally stage every process's flight-recorder dump (plus
telemetry snapshots and heartbeats) under
``artifacts/{fleet,elastic,p2p,asynckill,pod,partition}_dumps/``,
merge them into ONE causally consistent cross-worker timeline
(obs/fleetview.merge_timelines) at
``artifacts/{...}_merged_postmortem.jsonl``,
and assert the cross-process causal chains ci_fast re-gates with
``postmortem.py --merge --expect`` (ISSUE 15, ISSUE 18, ISSUE 19).

Usage: JAX_PLATFORMS=cpu python tools/chaos_smoke.py
"""

import glob
import os
import shutil
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

WORKER = os.path.join(_REPO, "tests", "chaos_worker.py")


def _stage_fleet_dumps(fleet_dir: str, dumps_dir: str,
                       merged_artifact: str, expects,
                       expected_workers) -> None:
    """Copy the round's per-process artifacts (fleet + worker
    flight-recorder dumps, telemetry snapshots, heartbeats) out of the
    tempdir into ``dumps_dir``, merge them into ONE cross-worker
    timeline at ``merged_artifact``, and assert every causal
    expectation — the same chains tools/ci_fast.sh re-gates with
    ``postmortem.py --merge --expect`` over the staged files."""
    from distributed_tensorflow_tpu.obs import fleetview as fv
    from distributed_tensorflow_tpu.obs import flightrec as fr

    shutil.rmtree(dumps_dir, ignore_errors=True)
    os.makedirs(dumps_dir, exist_ok=True)
    for pattern in ("fleet.jsonl", "flightrec-*.jsonl", "fleetsnap-*.json",
                    "heartbeat-*.json", "reqtrace-*.jsonl"):
        for src in glob.glob(os.path.join(fleet_dir, pattern)):
            shutil.copy(src, dumps_dir)
    worker_dumps = sorted(
        glob.glob(os.path.join(dumps_dir, "flightrec-*.jsonl")))
    for src in expected_workers:
        assert os.path.join(dumps_dir, f"flightrec-{src}.jsonl") \
            in worker_dumps, (src, worker_dumps)
    header, events, failures = fv.merge_timelines(
        os.path.join(dumps_dir, "fleet.jsonl"), worker_dumps,
        reason="chaos_smoke")
    assert not failures, failures
    fv.write_merged(merged_artifact, header, events)
    assert not fv.validate_merged_dump(merged_artifact)
    import importlib.util

    spec_loader = importlib.util.spec_from_file_location(
        "dtf_postmortem", os.path.join(_REPO, "tools", "postmortem.py"))
    pm = importlib.util.module_from_spec(spec_loader)
    spec_loader.loader.exec_module(pm)
    for spec in expects:
        assert fr.contains_in_order(events, pm.parse_expect(spec)), \
            (spec, [(e.get("src"), e["kind"]) for e in events])


def scheduler_invariants() -> None:
    from distributed_tensorflow_tpu.resilience import FaultClock
    from distributed_tensorflow_tpu.serve import scheduler as sl

    clk = FaultClock()
    s = sl.Scheduler(2, 16, clock=clk, max_queue=2)
    a = s.submit([1], deadline_s=1.0)
    b = s.submit([2], max_new_tokens=2)
    try:
        s.submit([3])
        raise AssertionError("QueueFull not raised at max_queue")
    except sl.QueueFull:
        pass
    clk.advance(2.0)
    expired = s.expire()  # a times out while still queued
    assert [r.uid for r in expired] == [a], expired
    assert s.finished[a].finish_reason == sl.FINISH_TIMEOUT
    placed = s.admit()
    assert [r.uid for _, r in placed] == [b], placed
    c = s.submit([4], deadline_s=0.5)
    assert s.admit()[0][1].uid == c  # c resident
    clk.advance(1.0)
    assert [r.uid for r in s.expire()] == [c]  # resident timeout frees slot
    assert s.cancel(b) is not None and s.cancel(b) is None  # idempotent
    assert s.close() == [] and s.closed
    try:
        s.submit([5])
        raise AssertionError("SchedulerClosed not raised after close()")
    except sl.SchedulerClosed:
        pass
    assert not s.has_work and sorted(s.finished) == [a, b, c]
    print("chaos_smoke: scheduler admission invariants OK")


def _run_worker(*args):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    p = subprocess.run(
        [sys.executable, WORKER, *args],
        capture_output=True, text=True, timeout=240, env=env,
    )
    if p.returncode != 0:
        raise AssertionError(
            f"chaos worker rc={p.returncode}:\n{p.stdout}\n{p.stderr}"
        )
    return p.stdout


def sigterm_resume_round() -> None:
    with tempfile.TemporaryDirectory(prefix="chaos_smoke_") as d:
        out = _run_worker(os.path.join(d, "ckpt"), "--steps", "6",
                          "--sigterm-at", "2")
        assert "CHAOS-PREEMPTED step=3" in out, out
        out = _run_worker(os.path.join(d, "ckpt"), "--steps", "6")
        assert "CHAOS-DONE step=6" in out, out
    print("chaos_smoke: SIGTERM -> coordinated save -> resume OK")


#: where the supervised round's flight-recorder dump lands — a stable
#: artifact so tools/ci_fast.sh can re-validate it with tools/postmortem.py
POSTMORTEM_ARTIFACT = os.environ.get(
    "DTF_CHAOS_POSTMORTEM",
    os.path.join(_REPO, "artifacts", "chaos_postmortem.jsonl"),
)

#: the causal story the supervised round's timeline must tell, in order
#: (shared with ci_fast.sh's postmortem gate)
POSTMORTEM_EXPECT = (
    "fault_fired[fault=sigterm],ckpt_save[trigger=preemption],"
    "sup_restart,fault_fired[fault=ckpt_corrupt],ckpt_quarantine,"
    "ckpt_restore[fallback=True]"
)


def supervised_recovery_round() -> None:
    """SIGTERM + truncated-newest-checkpoint in ONE supervised run: the
    Supervisor must restart in process, quarantine the corrupt newest
    step, fall back to an older valid one, and finish with finite
    params — and the flight recorder must have recorded the whole story
    (fault → preemption save → restart → quarantine → fallback restore
    in causal order; goodput gauge consistent with measured wall-clock).
    The dump is left at POSTMORTEM_ARTIFACT for the ci_fast postmortem
    gate."""
    os.makedirs(os.path.dirname(POSTMORTEM_ARTIFACT), exist_ok=True)
    with tempfile.TemporaryDirectory(prefix="chaos_smoke_sup_") as d:
        out = _run_worker(os.path.join(d, "ckpt"), "--supervise",
                          "--steps", "8", "--sigterm-at", "3",
                          "--corrupt-at-restart",
                          "--flightrec", POSTMORTEM_ARTIFACT)
        assert "CHAOS-SUPERVISED step=8" in out, out
        assert "finite=1" in out and "quarantined=1" in out, out
        assert "restarts=1" in out, out
        assert "ordered=1" in out, out
        assert "CHAOS-GOODPUT" in out and "ok=1" in out, out
    assert os.path.exists(POSTMORTEM_ARTIFACT), POSTMORTEM_ARTIFACT
    print("chaos_smoke: supervised SIGTERM + corrupt-newest -> "
          "fallback restore -> finish OK (postmortem at "
          f"{POSTMORTEM_ARTIFACT})")


#: where the nan-blame round's flight-recorder dump lands — a stable
#: artifact so tools/ci_fast.sh can gate on the anomaly causal chain
ANOMALY_POSTMORTEM_ARTIFACT = os.environ.get(
    "DTF_ANOMALY_POSTMORTEM",
    os.path.join(_REPO, "artifacts", "anomaly_postmortem.jsonl"),
)

#: the causal story the nan-blame round's timeline must tell, in order
#: (shared with ci_fast.sh's anomaly postmortem gate): recurring bad
#: batch fired → skipped in-graph → blamed into the quarantine file →
#: the SIGTERM'd restart restores and replays around the hole
ANOMALY_EXPECT = (
    "fault_fired[fault=nan_batch],anomaly_skip,anomaly_blame,ckpt_restore"
)


def nan_blame_round() -> None:
    """Recurring NaN at a fixed batch index + SIGTERM in ONE supervised
    run (tests/chaos_worker.py --anomaly): the in-graph guard no-ops
    the poisoned step (params never poisoned, so validate_before_save
    never refuses), the AnomalyPolicy skips it under budget and blames
    the exact (seed, index) into the quarantine file, and the
    preemption restart resumes THROUGH the quarantine hole to the
    target step with finite params. The dump is left at
    ANOMALY_POSTMORTEM_ARTIFACT for the ci_fast postmortem gate."""
    os.makedirs(os.path.dirname(ANOMALY_POSTMORTEM_ARTIFACT), exist_ok=True)
    with tempfile.TemporaryDirectory(prefix="chaos_smoke_nan_") as d:
        out = _run_worker(os.path.join(d, "ckpt"), "--supervise",
                          "--anomaly", "--steps", "8", "--nan-at", "3",
                          "--sigterm-at", "5",
                          "--flightrec", ANOMALY_POSTMORTEM_ARTIFACT)
        assert "CHAOS-ANOMALY skipped=1 quarantined=3 refused=0" in out, out
        assert "CHAOS-SUPERVISED step=8" in out, out
        assert "finite=1" in out and "ordered=1" in out, out
    assert os.path.exists(ANOMALY_POSTMORTEM_ARTIFACT)
    print("chaos_smoke: recurring NaN batch -> in-graph skip -> blame + "
          "quarantine -> restart past the hole -> finish OK (postmortem "
          f"at {ANOMALY_POSTMORTEM_ARTIFACT})")


#: where the fleet round's flight-recorder dump lands — a stable
#: artifact so tools/ci_fast.sh can gate on the gang-restart causal
#: chain with tools/postmortem.py --expect
FLEET_POSTMORTEM_ARTIFACT = os.environ.get(
    "DTF_FLEET_POSTMORTEM",
    os.path.join(_REPO, "artifacts", "fleet_postmortem.jsonl"),
)

#: the causal story the fleet round's timeline must tell, in order
#: (shared with ci_fast.sh's fleet postmortem gate)
FLEET_EXPECT = (
    "fleet_worker_dead,fleet_gang_stop,ckpt_restore[fallback=True],"
    "fleet_restart,fleet_done"
)

#: where the fleet round's per-process dumps are staged for the ci_fast
#: cross-worker merge gate, and where the merged timeline itself lands
FLEET_DUMPS_DIR = os.environ.get(
    "DTF_FLEET_DUMPS", os.path.join(_REPO, "artifacts", "fleet_dumps"))
FLEET_MERGED_ARTIFACT = os.environ.get(
    "DTF_FLEET_MERGED",
    os.path.join(_REPO, "artifacts", "fleet_merged_postmortem.jsonl"))

#: the CROSS-PROCESS causal story the merged fleet timeline must tell:
#: the gang stop precedes EVERY worker's incarnation-2 restore, which
#: precedes the fleet declaring the restarted gang live (shared with
#: ci_fast.sh's --merge gate; src pins the event to one process)
FLEET_MERGED_EXPECTS = (
    "fleet_gang_stop,ckpt_restore[src=w0i2],fleet_restart,fleet_done",
    "fleet_gang_stop,ckpt_restore[src=w1i2],fleet_restart,fleet_done",
)


def fleet_round() -> float:
    """Worker 1 hangs (heartbeats stop, process alive) → the fleet
    detects the death by missed heartbeats, gang-stops, and relaunches
    everyone at incarnation 2 from the latest common valid checkpoint.
    The flight-recorder dump is left at FLEET_POSTMORTEM_ARTIFACT for
    the ci_fast gate. Returns the gang restart's booked
    ``restart_recovery`` seconds — the baseline the elastic round's
    10x acceptance bar is measured against."""
    from distributed_tensorflow_tpu.obs.flightrec import FlightRecorder
    from distributed_tensorflow_tpu.obs.registry import Registry
    from distributed_tensorflow_tpu.resilience import RetryPolicy
    from distributed_tensorflow_tpu.resilience import fleet as fl

    os.makedirs(os.path.dirname(FLEET_POSTMORTEM_ARTIFACT), exist_ok=True)
    with tempfile.TemporaryDirectory(prefix="chaos_smoke_fleet_") as d:
        fleet_dir = os.path.join(d, "fleet")
        os.makedirs(fleet_dir)
        ckpt_dirs = [os.path.join(d, f"ckpt{i}") for i in range(2)]

        def launch(i, incarnation):
            args = [sys.executable, WORKER, ckpt_dirs[i], "--fleet",
                    "--fleet-dir", fleet_dir, "--worker-index", str(i),
                    "--steps", "6", "--flightrec-dir", fleet_dir]
            if i == 1:
                args += ["--hang-at", "3"]  # gated to incarnation 1
            env = dict(os.environ)
            env.pop("XLA_FLAGS", None)
            env["JAX_PLATFORMS"] = "cpu"
            # reviewed: a worker's stdout log stream, not durable state —
            # it feeds debugging, never a recovery decision
            log = open(os.path.join(  # dtflint: disable=atomic-durable-write
                fleet_dir, f"worker{i}-inc{incarnation}.log"), "w")
            try:
                return subprocess.Popen(args, stdout=log,
                                        stderr=subprocess.STDOUT, env=env)
            finally:
                log.close()

        from distributed_tensorflow_tpu.obs import fleetview as fv
        from distributed_tensorflow_tpu.obs import goodput

        rec = FlightRecorder()
        reg = Registry()
        fleet = fl.FleetSupervisor(
            launch, 2, fleet_dir,
            fl.FleetConfig(max_restarts=2,
                           backoff=RetryPolicy(base_s=0.0, jitter=0.0),
                           poll_s=0.2, heartbeat_timeout_s=20.0,
                           stall_timeout_s=600.0, launch_grace_s=180.0,
                           term_grace_s=5.0, snapshot_poll_s=0.4),
            ckpt_dirs=ckpt_dirs, registry=reg, flightrec=rec)
        out = fleet.run()
        assert out == {"restarts": 1, "incarnation": 2, "resizes": 0}, out
        assert fl.read_restore_step(fleet_dir) == 2, "common-step ceiling"
        # fleet observatory: the aggregator folded worker snapshots into
        # the fleet's registry — fleet-wide goodput from MERGED counters
        # and an own-clock staleness gauge per worker
        frac = reg.get(fv.FLEET_GOODPUT_FRACTION)
        assert frac is not None and 0.0 < frac.value <= 1.0, \
            "aggregator published no fleet_goodput_fraction"
        for i in range(2):
            assert reg.get(fv.FLEET_WORKER_STALENESS, worker=str(i)) \
                is not None, f"no staleness gauge for worker {i}"
        view = fleet.aggregator.view()
        assert view.get("train_steps_total") is not None, \
            "merged view has no fleet-wide union counters"
        rec.dump(FLEET_POSTMORTEM_ARTIFACT, reason="chaos_smoke_fleet")
        rec.dump(os.path.join(fleet_dir, "fleet.jsonl"),
                 reason="chaos_smoke_fleet")
        _stage_fleet_dumps(
            fleet_dir, FLEET_DUMPS_DIR, FLEET_MERGED_ARTIFACT,
            FLEET_MERGED_EXPECTS,
            expected_workers=("w0i1", "w0i2", "w1i2"))
        # the gang-restart baseline's price: the whole outage window
        # (stop -> relaunch -> restore -> live) in restart_recovery —
        # the elastic round below must beat it by >= 10x
        baseline = reg.get(goodput.WASTED_SECONDS,
                           cause=goodput.WASTE_RESTART_RECOVERY)
        baseline_rr = baseline.value if baseline is not None else 0.0
        assert baseline_rr > 0, "gang restart booked no recovery waste"
    assert os.path.exists(FLEET_POSTMORTEM_ARTIFACT)
    print("chaos_smoke: fleet hang -> missed-heartbeat death -> gang "
          "restart (incarnation 2, common ckpt) -> done OK (postmortem "
          f"at {FLEET_POSTMORTEM_ARTIFACT}; merged cross-worker timeline "
          f"at {FLEET_MERGED_ARTIFACT}; "
          f"restart_recovery={baseline_rr:.2f}s)")
    return baseline_rr


#: where the elastic round's flight-recorder dump lands — the ci_fast
#: gate checks the shrink -> rejoin causal chain on it
ELASTIC_POSTMORTEM_ARTIFACT = os.environ.get(
    "DTF_ELASTIC_POSTMORTEM",
    os.path.join(_REPO, "artifacts", "elastic_postmortem.jsonl"),
)

#: the causal story the elastic round's timeline must tell, in order
ELASTIC_EXPECT = "fleet_worker_dead,fleet_shrink,fleet_rejoin,fleet_done"

#: staging/merge artifacts for the elastic round's cross-worker gate
ELASTIC_DUMPS_DIR = os.environ.get(
    "DTF_ELASTIC_DUMPS", os.path.join(_REPO, "artifacts", "elastic_dumps"))
ELASTIC_MERGED_ARTIFACT = os.environ.get(
    "DTF_ELASTIC_MERGED",
    os.path.join(_REPO, "artifacts", "elastic_merged_postmortem.jsonl"))

#: the CROSS-PROCESS resize story: the fleet's hold plan precedes each
#: survivor's barrier pause, the shrink release precedes each
#: survivor's (and the replacement's) application of the new sharding —
#: i.e. every post-barrier step — and the rejoin precedes fleet_done
ELASTIC_MERGED_EXPECTS = (
    "fleet_worker_dead,fleet_hold,elastic_hold[src=w0i1],fleet_shrink,"
    "elastic_release[src=w0i1],fleet_rejoin,fleet_done",
    "fleet_worker_dead,fleet_hold,elastic_hold[src=w2i1],fleet_shrink,"
    "elastic_release[src=w2i1],fleet_rejoin,fleet_done",
    "fleet_shrink,elastic_release[src=w1i1],fleet_rejoin,fleet_done",
)


#: pacing shared by the replay-baseline and p2p elastic rounds — they
#: must be IDENTICAL runs up to the --p2p-catchup flags, or the rejoin
#: wall-time comparison below measures configuration, not catch-up.
#: Long enough that the survivors are still stepping (and therefore
#: serving catch-up requests) when the replacement's request lands —
#: and paced hard enough that the steps catch-up saves the joiner from
#: replaying dominate scheduling noise in the wall-time comparison.
ELASTIC_STEPS = 10
ELASTIC_STEP_SLEEP = 1.2


def _rejoin_wall_s(events) -> float:
    """Rejoin wall time on the FLEET's clock: replacement launch →
    fleet_done. The joiner is the round's straggler (its replay tail
    runs after the survivors finish), so this window prices exactly
    what catch-up exists to shrink."""
    t0 = next(e["t"] for e in events
              if e["kind"] == "fleet_launch" and e.get("rejoin"))
    t1 = next(e["t"] for e in events if e["kind"] == "fleet_done")
    return t1 - t0


def _shrink_rejoin_round(d: str, p2p: bool, outs: bool = False):
    """One 3-worker elastic shrink/rejoin round (worker 1 hard-dies at
    step 3, the fleet shrinks, relaunches the slot, the replacement
    rejoins). With ``p2p`` the workers run --p2p-catchup --async-save:
    cadence saves go through the background writer and the replacement
    imports a survivor's newest step instead of replaying from its own.
    Returns (fleet result, registry, recorder, fleet_dir, rejoin wall
    seconds)."""
    from distributed_tensorflow_tpu.obs.flightrec import FlightRecorder
    from distributed_tensorflow_tpu.obs.registry import Registry
    from distributed_tensorflow_tpu.resilience import RetryPolicy
    from distributed_tensorflow_tpu.resilience import fleet as fl

    fleet_dir = os.path.join(d, "fleet")
    os.makedirs(fleet_dir)
    ckpt_dirs = [os.path.join(d, f"ckpt{i}") for i in range(3)]
    launched = {}

    def launch(i, incarnation):
        n = launched.get(i, 0)
        launched[i] = n + 1
        args = [sys.executable, WORKER, ckpt_dirs[i], "--fleet",
                "--elastic", "--fleet-dir", fleet_dir,
                "--worker-index", str(i), "--steps", str(ELASTIC_STEPS),
                "--step-sleep", str(ELASTIC_STEP_SLEEP),
                "--flightrec-dir", fleet_dir]
        if p2p:
            args += ["--p2p-catchup", "--async-save"]
        if outs:
            args += ["--out", os.path.join(d, f"params{i}.npz")]
        if i == 1 and n == 0:
            args += ["--die-at", "3"]  # first launch only
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env["JAX_PLATFORMS"] = "cpu"
        # reviewed: a worker's stdout log stream, not durable state
        log = open(os.path.join(  # dtflint: disable=atomic-durable-write
            fleet_dir, f"worker{i}-n{n}.log"), "w")
        try:
            return subprocess.Popen(args, stdout=log,
                                    stderr=subprocess.STDOUT, env=env)
        finally:
            log.close()

    rec = FlightRecorder()
    reg = Registry()
    fleet = fl.FleetSupervisor(
        launch, 3, fleet_dir,
        fl.FleetConfig(max_restarts=2, elastic=True, min_workers=2,
                       backoff=RetryPolicy(base_s=0.0, jitter=0.0),
                       poll_s=0.2, heartbeat_timeout_s=20.0,
                       stall_timeout_s=600.0, launch_grace_s=180.0,
                       rejoin_grace_s=180.0, hold_timeout_s=120.0,
                       term_grace_s=5.0, snapshot_poll_s=0.4),
        ckpt_dirs=ckpt_dirs, registry=reg, flightrec=rec)
    out = fleet.run()
    assert out["restarts"] == 0, out
    assert out["resizes"] == 2, out  # one shrink + one rejoin
    return out, reg, rec, fleet_dir, _rejoin_wall_s(rec.events())


def elastic_round(baseline_rr: float) -> float:
    """One of 3 workers hard-dies mid-run (os._exit, no save, no final
    heartbeat) → the ELASTIC fleet shrinks the gang to the survivors at
    a barrier instead of gang-stopping, relaunches the slot, and the
    replacement rejoins at the next barrier — zero gang restarts, zero
    restart_recovery seconds (vs. the gang-restart baseline's full
    outage window: the >= 10x acceptance bar of ISSUE 12). The dump is
    left at ELASTIC_POSTMORTEM_ARTIFACT for the ci_fast gate. Returns
    the rejoin wall seconds — the DETERMINISTIC-REPLAY baseline the p2p
    catch-up round must beat."""
    from distributed_tensorflow_tpu.obs import goodput

    os.makedirs(os.path.dirname(ELASTIC_POSTMORTEM_ARTIFACT), exist_ok=True)
    with tempfile.TemporaryDirectory(prefix="chaos_smoke_elastic_") as d:
        out, reg, rec, fleet_dir, replay_wall = _shrink_rejoin_round(
            d, p2p=False)
        rr = reg.get(goodput.WASTED_SECONDS,
                     cause=goodput.WASTE_RESTART_RECOVERY)
        elastic_rr = rr.value if rr is not None else 0.0
        # ISSUE 12 acceptance: >= 10x drop vs the gang-restart baseline
        assert elastic_rr * 10 <= baseline_rr, (elastic_rr, baseline_rr)
        # the same chain ci_fast gates the dump on — asserted here too,
        # so this constant and the shell literal cannot drift apart
        from distributed_tensorflow_tpu.obs import flightrec as fr

        assert fr.contains_in_order(rec.events(), ELASTIC_EXPECT.split(",")), \
            rec.events()
        rec.dump(ELASTIC_POSTMORTEM_ARTIFACT, reason="chaos_smoke_elastic")
        rec.dump(os.path.join(fleet_dir, "fleet.jsonl"),
                 reason="chaos_smoke_elastic")
        _stage_fleet_dumps(
            fleet_dir, ELASTIC_DUMPS_DIR, ELASTIC_MERGED_ARTIFACT,
            ELASTIC_MERGED_EXPECTS,
            expected_workers=("w0i1", "w1i1", "w2i1"))
    assert os.path.exists(ELASTIC_POSTMORTEM_ARTIFACT)
    print("chaos_smoke: elastic death -> shrink@barrier -> replacement "
          "rejoin -> done OK (restart_recovery "
          f"{elastic_rr:.2f}s vs gang baseline {baseline_rr:.2f}s; "
          f"replay rejoin wall {replay_wall:.2f}s; "
          f"postmortem at {ELASTIC_POSTMORTEM_ARTIFACT}; merged "
          f"cross-worker timeline at {ELASTIC_MERGED_ARTIFACT})")
    return replay_wall


#: staging/merge artifacts for the p2p catch-up round's cross-worker gate
P2P_DUMPS_DIR = os.environ.get(
    "DTF_P2P_DUMPS", os.path.join(_REPO, "artifacts", "p2p_dumps"))
P2P_MERGED_ARTIFACT = os.environ.get(
    "DTF_P2P_MERGED",
    os.path.join(_REPO, "artifacts", "p2p_merged_postmortem.jsonl"))

#: the CROSS-PROCESS catch-up story the merged p2p timeline must tell
#: (shared with ci_fast.sh's --merge gate). Two chains, not one:
#: offer→import causality is enforced by the file protocol itself (the
#: joiner can only import a published offer), but the two events land
#: ~one poll apart on DIFFERENT process clocks, finer than the merged
#: timeline's alignment can order — so each chain anchors one side of
#: the exchange against the fleet's own events instead. Which survivor
#: claims the request is a race, so catchup_offer carries no src pin.
P2P_MERGED_EXPECTS = (
    "fleet_worker_dead,catchup_offer,fleet_done",
    "fleet_worker_dead,catchup_restore[src=w1i1],fleet_rejoin,fleet_done",
)


def p2p_catchup_round(replay_wall: float) -> None:
    """The elastic round again, with --p2p-catchup --async-save: the
    replacement imports a live survivor's newest async-committed step
    over the file control plane instead of replaying from its own
    (older) checkpoint — the SAME run otherwise, so its rejoin wall
    time must come in BELOW the deterministic-replay baseline. Final
    params of every worker must be bit-identical to an uninterrupted
    same-seed straight run: catch-up moves state, never the trajectory
    (ISSUE 18 acceptance)."""
    import numpy as np

    with tempfile.TemporaryDirectory(prefix="chaos_smoke_p2p_") as d:
        out, reg, rec, fleet_dir, p2p_wall = _shrink_rejoin_round(
            d, p2p=True, outs=True)
        events = rec.events()
        rec.dump(os.path.join(fleet_dir, "fleet.jsonl"),
                 reason="chaos_smoke_p2p")
        _stage_fleet_dumps(
            fleet_dir, P2P_DUMPS_DIR, P2P_MERGED_ARTIFACT,
            P2P_MERGED_EXPECTS,
            expected_workers=("w0i1", "w1i1", "w2i1"))
        # the joiner must have caught up VIA A PEER, not the fallback:
        # its import step must beat the step-2 checkpoint its own dir
        # held when it died
        import json as _json

        with open(os.path.join(P2P_MERGED_ARTIFACT)) as f:
            merged = [_json.loads(line) for line in f if line.strip()]
        restores = [e for e in merged if e.get("kind") == "catchup_restore"]
        assert restores, "no catchup_restore in the merged p2p timeline"
        assert int(restores[0]["step"]) > 2, restores
        assert not any(e.get("kind") == "catchup_fallback" for e in merged), \
            "joiner fell back to replay in the p2p round"
        # rejoin must be CHEAPER than replaying the same distance
        assert p2p_wall < replay_wall, (p2p_wall, replay_wall)

        # bit-identity: an uninterrupted straight run (same seed, same
        # target step, one process, no fleet) must agree with EVERY
        # worker's final params — the death, the shrink, the import and
        # the replay all left the trajectory untouched
        straight = os.path.join(d, "straight.npz")
        stdout = _run_worker(os.path.join(d, "straight_ckpt"),
                             "--steps", str(ELASTIC_STEPS),
                             "--out", straight)
        assert f"CHAOS-DONE step={ELASTIC_STEPS}" in stdout, stdout
        ref = dict(np.load(straight))
        for i in range(3):
            got = dict(np.load(os.path.join(d, f"params{i}.npz")))
            assert set(got) == set(ref), (i, set(got), set(ref))
            for k in ref:
                assert np.array_equal(ref[k], got[k]), \
                    f"worker {i} params[{k}] diverged from the straight run"
    print("chaos_smoke: p2p catch-up rejoin OK (rejoin wall "
          f"{p2p_wall:.2f}s vs replay baseline {replay_wall:.2f}s; "
          f"import step {int(restores[0]['step'])}; params bit-identical "
          f"to the straight run; merged timeline at {P2P_MERGED_ARTIFACT})")


#: staging/merge artifacts for the async-commit-kill round's gate
ASYNCKILL_DUMPS_DIR = os.environ.get(
    "DTF_ASYNCKILL_DUMPS",
    os.path.join(_REPO, "artifacts", "asynckill_dumps"))
ASYNCKILL_MERGED_ARTIFACT = os.environ.get(
    "DTF_ASYNCKILL_MERGED",
    os.path.join(_REPO, "artifacts", "asynckill_merged_postmortem.jsonl"))

#: the torn-write invisibility story (the ISSUE 18 ci gate, verbatim):
#: the async save began, the SIGKILL landed INSIDE the commit window
#: (shards written, manifest not yet published), and the relaunched
#: gang restored the PREVIOUS step with fallback=False — the strict
#: path, which would have raised on any torn state, proving the dead
#: step never became visible
ASYNCKILL_MERGED_EXPECTS = (
    "ckpt_async_begin,fault_fired[fault=async_commit_kill],"
    "ckpt_restore[fallback=False]",
    "fleet_worker_dead,fleet_gang_stop,fleet_restart,fleet_done",
)


def async_kill_round() -> None:
    """SIGKILL inside the async commit window: worker 1's background
    writer dies BETWEEN writing its shards and publishing the manifest
    (faults.AsyncCommitKill through the production save-hook seam). The
    torn step must be invisible everywhere — the fleet's common-step
    ceiling lands on the previous step, both relaunched workers restore
    it with fallback=False (strict verify, no quarantine), and the run
    finishes. ISSUE 18's first acceptance E2E."""
    from distributed_tensorflow_tpu.obs.flightrec import FlightRecorder
    from distributed_tensorflow_tpu.obs.registry import Registry
    from distributed_tensorflow_tpu.resilience import RetryPolicy
    from distributed_tensorflow_tpu.resilience import fleet as fl

    with tempfile.TemporaryDirectory(prefix="chaos_smoke_akill_") as d:
        fleet_dir = os.path.join(d, "fleet")
        os.makedirs(fleet_dir)
        ckpt_dirs = [os.path.join(d, f"ckpt{i}") for i in range(2)]

        def launch(i, incarnation):
            args = [sys.executable, WORKER, ckpt_dirs[i], "--fleet",
                    "--fleet-dir", fleet_dir, "--worker-index", str(i),
                    "--steps", "8", "--async-save", "--strict-restore",
                    "--step-sleep", "0.2", "--flightrec-dir", fleet_dir]
            if i == 1:
                args += ["--async-kill-at", "4"]  # gated to incarnation 1
            env = dict(os.environ)
            env.pop("XLA_FLAGS", None)
            env["JAX_PLATFORMS"] = "cpu"
            # reviewed: a worker's stdout log stream, not durable state
            log = open(os.path.join(  # dtflint: disable=atomic-durable-write
                fleet_dir, f"worker{i}-inc{incarnation}.log"), "w")
            try:
                return subprocess.Popen(args, stdout=log,
                                        stderr=subprocess.STDOUT, env=env)
            finally:
                log.close()

        rec = FlightRecorder()
        reg = Registry()
        fleet = fl.FleetSupervisor(
            launch, 2, fleet_dir,
            fl.FleetConfig(max_restarts=2,
                           backoff=RetryPolicy(base_s=0.0, jitter=0.0),
                           poll_s=0.2, heartbeat_timeout_s=20.0,
                           stall_timeout_s=600.0, launch_grace_s=180.0,
                           term_grace_s=5.0, snapshot_poll_s=0.4),
            ckpt_dirs=ckpt_dirs, registry=reg, flightrec=rec)
        out = fleet.run()
        assert out == {"restarts": 1, "incarnation": 2, "resizes": 0}, out
        # the torn step-4 write must have been invisible to the ceiling:
        # the newest step BOTH workers can verify is the previous save
        assert fl.read_restore_step(fleet_dir) == 2, \
            fl.read_restore_step(fleet_dir)
        for i, ck in enumerate(ckpt_dirs):
            # strict restore never quarantined anything, and no staging
            # residue survived the relaunch
            assert not os.path.isdir(os.path.join(ck, ".corrupt")), \
                f"worker {i} quarantined a step under strict restore"
            pending = os.path.join(ck, ".pending")
            assert not os.path.isdir(pending) or not os.listdir(pending), \
                f"worker {i} left staging residue: {os.listdir(pending)}"
            assert fl.newest_valid_step(ck) is not None
        rec.dump(os.path.join(fleet_dir, "fleet.jsonl"),
                 reason="chaos_smoke_asynckill")
        _stage_fleet_dumps(
            fleet_dir, ASYNCKILL_DUMPS_DIR, ASYNCKILL_MERGED_ARTIFACT,
            ASYNCKILL_MERGED_EXPECTS,
            expected_workers=("w0i1", "w1i1", "w0i2", "w1i2"))
    print("chaos_smoke: SIGKILL mid-async-commit -> torn step invisible "
          "-> gang restored previous step (fallback=False, zero "
          "quarantines) -> done OK (merged timeline at "
          f"{ASYNCKILL_MERGED_ARTIFACT})")


#: staging/merge artifacts for the serve-fleet round's cross-process gate
SERVE_FLEET_DUMPS_DIR = os.environ.get(
    "DTF_SERVE_FLEET_DUMPS",
    os.path.join(_REPO, "artifacts", "serve_fleet_dumps"))
SERVE_FLEET_MERGED_ARTIFACT = os.environ.get(
    "DTF_SERVE_FLEET_MERGED",
    os.path.join(_REPO, "artifacts", "serve_fleet_merged_postmortem.jsonl"))

#: the CROSS-PROCESS failover story the merged serve-fleet timeline must
#: tell (shared with ci_fast.sh's --merge gate): the SIGKILL is detected
#: (serve_replica_dead, fleet clock), the victim's in-flight requests
#: return to their lane heads (serve_requeue), a SURVIVOR admits a
#: re-prefilled request (serve_admit, worker clock — aligned through the
#: serve_route dispatch/ACK handshake), and the fleet closes the
#: timeline (fleet_done)
SERVE_FLEET_MERGED_EXPECT = (
    "serve_replica_dead,serve_requeue,serve_admit,fleet_done")


def serve_fleet_round() -> None:
    """SIGKILL one of two subprocess serve replicas mid-stream
    (serve/replica.py workers under ServeFleetSupervisor): the
    supervisor sees the exit, the router requeues the victim's
    in-flight requests at their lane heads, the survivor re-prefills
    and finishes EVERY stream — no request lost — and drains leak-free
    (the terminal block-accounting audit; the corpse never writes one,
    which is the point). The per-process dumps are staged for the
    ci_fast merge gate."""
    from distributed_tensorflow_tpu.obs.flightrec import FlightRecorder
    from distributed_tensorflow_tpu.obs.registry import Registry
    from distributed_tensorflow_tpu.obs.reqtrace import ReqTrace
    from distributed_tensorflow_tpu.serve import fleet as sf
    from distributed_tensorflow_tpu.serve import router as rt

    with tempfile.TemporaryDirectory(prefix="chaos_smoke_serve_") as d:
        fleet_dir = os.path.join(d, "fleet")
        os.makedirs(fleet_dir)

        def launch(i, incarnation):
            args = [sys.executable, "-m",
                    "distributed_tensorflow_tpu.serve.replica",
                    "--workdir", fleet_dir, "--index", str(i),
                    "--incarnation", str(incarnation),
                    "--slots", "2", "--seed", "0"]
            env = dict(os.environ)
            env.pop("XLA_FLAGS", None)
            env["JAX_PLATFORMS"] = "cpu"
            # reviewed: a replica's stdout log stream, not durable state
            log = open(os.path.join(  # dtflint: disable=atomic-durable-write
                fleet_dir, f"replica{i}-inc{incarnation}.log"), "w")
            try:
                proc = subprocess.Popen(args, stdout=log,
                                        stderr=subprocess.STDOUT, env=env)
            finally:
                log.close()
            return sf.SubprocessReplica(proc, fleet_dir, i, incarnation)

        rec = FlightRecorder()
        reg = Registry()
        # router half of the request ledger; each serve/replica.py
        # worker dumps its own half per pump (reqtrace-w<i>i<k>.jsonl),
        # so the SIGKILLed victim's spans survive for the merge gate
        router_trace = ReqTrace(src="router")
        router = rt.Router(policy="prefix", max_outstanding=2,
                           registry=reg, flightrec=rec,
                           reqtrace=router_trace)
        sup = sf.ServeFleetSupervisor(
            launch, 2, router=router, workdir=fleet_dir,
            registry=reg, flightrec=rec, poll_s=0.02,
            heartbeat_timeout_s=60.0, stall_timeout_s=600.0,
            launch_grace_s=180.0, snapshot_poll_s=0.4)
        sup.start()

        # two shared system prompts so both replicas get a prefix home
        import random as _random
        rng = _random.Random(0)
        groups = [[rng.randrange(256) for _ in range(24)] for _ in range(2)]
        total = 10
        for i in range(total):
            g = groups[i % 2]
            lane = rt.LANE_INTERACTIVE if i % 2 == 0 else rt.LANE_BATCH
            router.submit(g + [rng.randrange(256) for _ in range(6)],
                          max_new_tokens=12, lane=lane, prefix_len=24)

        # pump until a replica is mid-stream (an in-flight request with
        # delivered tokens), then SIGKILL it
        import time as _time
        deadline = _time.monotonic() + 180.0
        victim = None
        while victim is None:
            assert _time.monotonic() < deadline, \
                "no replica went mid-stream within 180s"
            sup.pump()
            for w in sorted(sup.replicas):
                rids = router.outstanding.get(w, ())
                if any(router.requests[r].delivered for r in rids):
                    victim = w
                    break
            _time.sleep(0.02)
        sup.replicas[victim].handle.kill()
        sup.run()
        survivors = sorted(sup.replicas)
        sup.stop(timeout_s=60.0)

        assert len(router.finished) == total, (
            f"lost requests: {len(router.finished)}/{total}")
        assert all(r.finish_reason in ("max_new_tokens", "eos")
                   for r in router.finished.values()), router.finished
        assert sup.deaths == 1 and victim not in survivors
        requeues = int(reg.get("router_requeues_total").value)
        assert requeues >= 1, "kill landed between streams; no requeue"
        for i in survivors:
            audit = sup.drained.get(i)
            assert audit and audit.get("leak_free"), (i, audit)
        assert victim not in sup.drained  # a corpse never writes the audit

        rec.dump(os.path.join(fleet_dir, "fleet.jsonl"),
                 reason="chaos_smoke_serve_fleet")
        router_trace.dump(os.path.join(fleet_dir, "reqtrace-router.jsonl"),
                          reason="chaos_smoke_serve_fleet")
        _stage_fleet_dumps(
            fleet_dir, SERVE_FLEET_DUMPS_DIR, SERVE_FLEET_MERGED_ARTIFACT,
            (SERVE_FLEET_MERGED_EXPECT,),
            expected_workers=tuple(f"w{i}i0" for i in survivors))
        # the victim's request-ledger half must have survived the
        # SIGKILL: its per-pump dump is written BEFORE token events
        # become visible (the ci_fast trace gate merges these)
        assert os.path.exists(os.path.join(
            SERVE_FLEET_DUMPS_DIR, f"reqtrace-w{victim}i0.jsonl")), (
            "SIGKILLed replica left no request-trace dump")
    print("chaos_smoke: serve replica SIGKILL mid-stream -> requeue at "
          f"lane head -> survivor re-prefill -> all {total} streams "
          f"finished, {requeues} requeued, survivors leak-free OK "
          f"(merged timeline at {SERVE_FLEET_MERGED_ARTIFACT})")


#: staging/merge artifacts for the two-pod outage round's gate
POD_DUMPS_DIR = os.environ.get(
    "DTF_POD_DUMPS", os.path.join(_REPO, "artifacts", "pod_dumps"))
POD_MERGED_ARTIFACT = os.environ.get(
    "DTF_POD_MERGED",
    os.path.join(_REPO, "artifacts", "pod_merged_postmortem.jsonl"))

#: the hierarchical-fault-domain story the merged two-pod timeline
#: must tell (shared with ci_fast.sh's --merge gate): pod B's outage
#: is detected and restarted POD-LOCALLY (pod_outage → pod_restart →
#: pod_rejoin, all tagged pod=1), each relaunched pod-B worker
#: strict-restores the pod's OWN quorum ceiling (fallback=False —
#: nothing to fall back from: the per-pod intersection is exact)
#: before the pod is declared live again, and the planet still
#: reaches ONE global fleet_done. src pins ``p<pod>w<worker>i<inc>``.
POD_MERGED_EXPECTS = (
    "pod_outage[pod=1],pod_restart[pod=1],pod_rejoin[pod=1],fleet_done",
    "pod_outage[pod=1],ckpt_restore[src=p1w0i2,fallback=False],"
    "pod_rejoin[pod=1],fleet_done",
    "pod_outage[pod=1],ckpt_restore[src=p1w1i2,fallback=False],"
    "pod_rejoin[pod=1],fleet_done",
)

#: pacing for the two-pod rounds: long enough that the healthy pod is
#: still stepping across pod B's whole outage window (kill → detect →
#: relaunch → restore → live), so the forward-progress assertion has
#: steps to count
POD_STEPS = 14
POD_STEP_SLEEP = 0.6


def pod_outage_round() -> None:
    """Pod B (2 of 2 workers) SIGKILLs itself at step 4 (PodOutage,
    gated to epoch 1 / incarnation 1) → pod B's OWN supervisor
    gang-restarts just that pod from pod B's per-pod quorum ceiling
    (the step-4 save lands before the kill, so the ceiling is exactly
    4 and the strict restore needs no fallback), while pod A never
    stops stepping — the ISSUE 19 acceptance: one pod's outage
    degrades, never gang-stops, the planet. Final params of all four
    workers must be bit-identical to an uninterrupted same-seed
    straight run."""
    import json as _json

    import numpy as np

    from distributed_tensorflow_tpu.obs.flightrec import FlightRecorder
    from distributed_tensorflow_tpu.obs.registry import Registry
    from distributed_tensorflow_tpu.resilience import RetryPolicy
    from distributed_tensorflow_tpu.resilience import fleet as fl
    from distributed_tensorflow_tpu.resilience import podfleet as pf

    with tempfile.TemporaryDirectory(prefix="chaos_smoke_pod_") as d:
        fleet_dir = os.path.join(d, "fleet")
        os.makedirs(fleet_dir)
        ckpt_dirs = [[os.path.join(d, f"ckpt_p{p}w{i}") for i in range(2)]
                     for p in range(2)]

        def launch(p, i, incarnation):
            args = [sys.executable, WORKER, ckpt_dirs[p][i], "--fleet",
                    "--fleet-dir", pf.pod_dir(fleet_dir, p),
                    "--pod", str(p), "--worker-index", str(i),
                    "--steps", str(POD_STEPS), "--strict-restore",
                    "--step-sleep", str(POD_STEP_SLEEP),
                    "--out", os.path.join(d, f"params_p{p}w{i}.npz"),
                    "--flightrec-dir", fleet_dir]
            if p == 1:
                # gated to (epoch 1, incarnation 1): fire-once across
                # the TWO-LEVEL fence — the relaunched pod-B workers
                # (incarnation 2) and any later epoch never re-die
                args += ["--pod-outage-at", "4", "--fault-epoch", "1"]
            env = dict(os.environ)
            env.pop("XLA_FLAGS", None)
            env["JAX_PLATFORMS"] = "cpu"
            # reviewed: a worker's stdout log stream, not durable state
            log = open(os.path.join(  # dtflint: disable=atomic-durable-write
                fleet_dir, f"pod{p}w{i}-inc{incarnation}.log"), "w")
            try:
                return subprocess.Popen(args, stdout=log,
                                        stderr=subprocess.STDOUT, env=env)
            finally:
                log.close()

        rec = FlightRecorder()
        reg = Registry()
        fleet = pf.PodFleetSupervisor(
            launch, 2, 2, fleet_dir,
            cfg=fl.FleetConfig(max_restarts=2,
                               backoff=RetryPolicy(base_s=0.0, jitter=0.0),
                               poll_s=0.2, heartbeat_timeout_s=20.0,
                               stall_timeout_s=600.0, launch_grace_s=180.0,
                               term_grace_s=5.0, snapshot_poll_s=0.4),
            ckpt_dirs=ckpt_dirs, registry=reg, flightrec=rec)
        out = fleet.run()
        assert out["epoch"] == 1 and out["restarts"] == 1, out
        assert out["pod_restarts"] == {0: 0, 1: 1}, out
        # hierarchical restore ceilings: the restarted pod resumed at
        # ITS OWN per-pod quorum; the healthy pod never restarted, so
        # its dir holds no ceiling at all — pod B's outage could not
        # drag pod A's restore point anywhere
        assert fl.read_restore_step(pf.pod_dir(fleet_dir, 1)) == 4, \
            fl.read_restore_step(pf.pod_dir(fleet_dir, 1))
        assert fl.read_restore_step(pf.pod_dir(fleet_dir, 0)) is None
        # SIGKILL classifies transient: exactly one pod-local restart
        restarted = reg.get(pf.POD_RESTARTS_TOTAL, cause="transient")
        assert restarted is not None and restarted.value == 1, restarted
        rec.dump(os.path.join(fleet_dir, "fleet.jsonl"),
                 reason="chaos_smoke_pod")
        _stage_fleet_dumps(
            fleet_dir, POD_DUMPS_DIR, POD_MERGED_ARTIFACT,
            POD_MERGED_EXPECTS,
            expected_workers=("p0w0i1", "p0w1i1", "p1w0i1", "p1w1i1",
                              "p1w0i2", "p1w1i2"))
        # forward progress THROUGH the outage: inside the
        # pod_outage → pod_rejoin window, at least one pod-A worker
        # must have recorded >= 2 strictly-increasing step_end events
        # — pod A never held for pod B. The merged timeline proves the
        # CAUSAL chain (the expects above); window MEMBERSHIP is
        # checked on the staged raw dumps, because every chaos_smoke
        # process shares this host's monotonic clock, while the merged
        # view places each dump at its earliest causally-consistent
        # offset — a sound lower bound, but biased early by the
        # worker's whole import/compile window
        def _raw(path):
            with open(path) as f:
                return [e for e in (_json.loads(line) for line in f
                                    if line.strip()) if e.get("kind")]

        fleet_evs = _raw(os.path.join(POD_DUMPS_DIR, "fleet.jsonl"))
        t_out = next(e["t"] for e in fleet_evs
                     if e["kind"] == "pod_outage"
                     and str(e.get("pod")) == "1")
        t_rejoin = next(e["t"] for e in fleet_evs
                        if e["kind"] == "pod_rejoin"
                        and str(e.get("pod")) == "1")
        in_window: dict[str, list[int]] = {}
        for w in range(2):
            evs = _raw(os.path.join(POD_DUMPS_DIR,
                                    f"flightrec-p0w{w}i1.jsonl"))
            in_window[f"p0w{w}i1"] = [
                int(e["step"]) for e in evs
                if e["kind"] == "step_end" and t_out <= e["t"] <= t_rejoin]
        progressed = [s for s in in_window.values()
                      if len(s) >= 2 and s == sorted(set(s))]
        assert progressed, ("no pod-A worker stepped inside pod B's "
                            "outage window", in_window, t_out, t_rejoin)

        # bit-identity: an uninterrupted straight run (same seed, same
        # target step, one process, no pods) must agree with EVERY
        # worker's final params — the outage, the pod-local restart
        # and the strict quorum restore all left the trajectory alone
        straight = os.path.join(d, "straight.npz")
        stdout = _run_worker(os.path.join(d, "straight_ckpt"),
                             "--steps", str(POD_STEPS), "--out", straight)
        assert f"CHAOS-DONE step={POD_STEPS}" in stdout, stdout
        ref = dict(np.load(straight))
        for p in range(2):
            for i in range(2):
                got = dict(np.load(
                    os.path.join(d, f"params_p{p}w{i}.npz")))
                assert set(got) == set(ref), (p, i, set(got), set(ref))
                for k in ref:
                    assert np.array_equal(ref[k], got[k]), \
                        f"pod {p} worker {i} params[{k}] diverged"
    print("chaos_smoke: pod B outage -> pod-local gang restart at pod "
          "quorum (ceiling 4, fallback=False) -> pod A stepped through "
          "the window -> params bit-identical to the straight run OK "
          f"(merged timeline at {POD_MERGED_ARTIFACT})")


#: staging/merge artifacts for the control-plane partition round's gate
PARTITION_DUMPS_DIR = os.environ.get(
    "DTF_PARTITION_DUMPS",
    os.path.join(_REPO, "artifacts", "partition_dumps"))
PARTITION_MERGED_ARTIFACT = os.environ.get(
    "DTF_PARTITION_MERGED",
    os.path.join(_REPO, "artifacts", "partition_merged_postmortem.jsonl"))

#: the partition-fencing story (shared with ci_fast.sh's --merge
#: gate): the partition fault fires in pod B's worker, the pod
#: supervisor FENCES (heartbeat file stale + process alive + beats
#: seen before = control plane partitioned, not a dead worker) and
#: unfences when the writes come back — while pod A's merely-SLOW
#: beats never trip a fence at all. No restart events may appear:
#: fencing exists precisely so a stale file never triggers the
#: relaunch that would double-train the live worker's batch range.
PARTITION_MERGED_EXPECTS = (
    "fault_fired[fault=control_plane_partition],pod_fence[pod=1],"
    "pod_unfence[pod=1],fleet_done",
    "fault_fired[fault=slow_control_plane],fleet_done",
)


def partition_round() -> None:
    """Pod B's worker redirects its heartbeat writes into a shadow
    file for 5 paced steps (~5s, past the 3s heartbeat timeout) while
    it KEEPS TRAINING; pod A's worker merely delays each beat by 0.3s
    (well inside the timeout — the pulse thread keeps its file fresh
    regardless). The pod supervisor must judge partition, not death:
    pod_fence, zero restarts, no split-brain relaunch — then
    pod_unfence when the window heals, and both pods finish. The
    gray-failure contrast (slow != dead) is the round's second
    assertion."""
    import json as _json

    from distributed_tensorflow_tpu.obs.flightrec import FlightRecorder
    from distributed_tensorflow_tpu.obs.registry import Registry
    from distributed_tensorflow_tpu.resilience import RetryPolicy
    from distributed_tensorflow_tpu.resilience import fleet as fl
    from distributed_tensorflow_tpu.resilience import podfleet as pf

    with tempfile.TemporaryDirectory(prefix="chaos_smoke_part_") as d:
        fleet_dir = os.path.join(d, "fleet")
        os.makedirs(fleet_dir)
        ckpt_dirs = [[os.path.join(d, f"ckpt_p{p}")] for p in range(2)]

        def launch(p, i, incarnation):
            args = [sys.executable, WORKER, ckpt_dirs[p][i], "--fleet",
                    "--fleet-dir", pf.pod_dir(fleet_dir, p),
                    "--pod", str(p), "--worker-index", str(i),
                    "--steps", "10", "--step-sleep", "1.0",
                    "--fault-epoch", "1",
                    "--flightrec-dir", fleet_dir]
            if p == 1:
                args += ["--partition-at", "3", "--partition-steps", "5"]
            else:
                args += ["--slow-beat-at", "3", "--slow-beat-delay",
                         "0.3", "--slow-beat-steps", "3"]
            env = dict(os.environ)
            env.pop("XLA_FLAGS", None)
            env["JAX_PLATFORMS"] = "cpu"
            # reviewed: a worker's stdout log stream, not durable state
            log = open(os.path.join(  # dtflint: disable=atomic-durable-write
                fleet_dir, f"pod{p}w{i}-inc{incarnation}.log"), "w")
            try:
                return subprocess.Popen(args, stdout=log,
                                        stderr=subprocess.STDOUT, env=env)
            finally:
                log.close()

        rec = FlightRecorder()
        reg = Registry()
        fleet = pf.PodFleetSupervisor(
            launch, 2, 1, fleet_dir,
            cfg=fl.FleetConfig(max_restarts=2,
                               backoff=RetryPolicy(base_s=0.0, jitter=0.0),
                               poll_s=0.2, heartbeat_timeout_s=3.0,
                               stall_timeout_s=600.0, launch_grace_s=180.0,
                               term_grace_s=5.0, snapshot_poll_s=0.4),
            ckpt_dirs=ckpt_dirs, registry=reg, flightrec=rec)
        out = fleet.run()
        assert out["restarts"] == 0 and out["pod_restarts"] == {0: 0, 1: 0}, \
            out
        # the shadow file is where the partitioned writes actually
        # went — proof the heartbeat path itself was severed, not the
        # worker paused
        shadow = fl.heartbeat_path(pf.pod_dir(fleet_dir, 1), 0) \
            + ".partitioned"
        assert os.path.exists(shadow), shadow
        rec.dump(os.path.join(fleet_dir, "fleet.jsonl"),
                 reason="chaos_smoke_partition")
        _stage_fleet_dumps(
            fleet_dir, PARTITION_DUMPS_DIR, PARTITION_MERGED_ARTIFACT,
            PARTITION_MERGED_EXPECTS,
            expected_workers=("p0w0i1", "p1w0i1"))
        with open(PARTITION_MERGED_ARTIFACT) as f:
            merged = [_json.loads(line) for line in f if line.strip()]
        # no split-brain: the stale heartbeat file never became a
        # restart — no outage/restart/gang events anywhere, and
        # exactly one launch per worker (nobody double-trained pod
        # B's batch range while its original was still alive)
        banned = {"pod_outage", "pod_restart", "fleet_gang_stop",
                  "fleet_restart", "fleet_worker_dead"}
        hit = [e for e in merged if e.get("kind") in banned]
        assert not hit, hit
        launches = [e for e in merged if e.get("kind") == "fleet_launch"]
        assert len(launches) == 2, launches
        # ONE fence for the whole window (the fence clock must not
        # flap per poll round — fence_timeout_s escalation depends on
        # t0 surviving the suppressed rounds), healed by ONE unfence
        fences = [e for e in merged if e.get("kind") == "pod_fence"]
        unfences = [e for e in merged if e.get("kind") == "pod_unfence"]
        assert len(fences) == 1 and len(unfences) == 1, (fences, unfences)
        # slow != dead: the paced pod never tripped a fence
        assert str(fences[0].get("pod")) == "1", fences
    print("chaos_smoke: control-plane partition -> fenced (no restart, "
          "no split-brain) -> unfenced on heal; slow beats judged LIVE "
          f"OK (merged timeline at {PARTITION_MERGED_ARTIFACT})")


def main() -> int:
    scheduler_invariants()
    sigterm_resume_round()
    supervised_recovery_round()
    nan_blame_round()
    baseline_rr = fleet_round()
    replay_wall = elastic_round(baseline_rr)
    p2p_catchup_round(replay_wall)
    async_kill_round()
    serve_fleet_round()
    pod_outage_round()
    partition_round()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
