#!/usr/bin/env python
"""Perf-regression sentinel over provenance-stamped bench JSONs.

Compares two or more bench result files (the ``--json`` outputs of
tools/bench_serve.py / bench.py, each carrying the ``provenance`` block
``obs.scaling.stamp_provenance`` wrote) in the order given — oldest
first, candidate last — and exits nonzero when a named metric regressed
by more than the threshold between the first and last run.

Provenance is a precondition, not decoration: a throughput "regression"
measured on a different platform or device kind is not a regression,
it is a category error — and a run with no ``git_sha`` cannot be pinned
to a commit at all. The tool therefore REFUSES to compare (exit 2,
before any metric math) when:

- a run is missing its ``provenance`` block or its ``git_sha``;
- runs disagree on ``platform`` or ``device_kind`` (the masquerade
  guard — the same rule ``validate_scaling_report`` applies inside one
  report, applied across runs).

``git_sha`` *differing* across runs is fine — that difference is the
comparison axis.

Metrics are dotted paths into the result dict
(``routed.lanes.interactive.ttft_p99_ms``, ``tokens_per_sec``).
Direction is inferred from the name — latency-shaped metrics
(``*_ms``, ``*_s``, ``*_seconds``, ``wall_s``) regress UP, everything
else (throughput, counts) regresses DOWN — and can be forced per metric
with a ``metric:lower`` / ``metric:higher`` suffix naming which
direction is better.

Usage:
    python tools/bench_trend.py old.json new.json \
        --metric tokens_per_sec --metric ttft_p99_ms --max-regress-pct 10
"""

import argparse
import json
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

#: name suffixes read as "lower is better" (latency/duration shapes)
_LOWER_BETTER_SUFFIXES = ("_ms", "_s", "_seconds")


def lookup(result: dict, path: str):
    """Resolve a dotted path; returns None when any hop is missing.

    Numeric parts index into lists (``cells.0.steps_per_sec``) so sweep
    reports — whose leaves live inside a ``cells`` array — are reachable
    with the same dotted syntax as flat bench dicts.
    """
    node = result
    for part in path.split("."):
        if isinstance(node, list) and part.isdigit():
            if int(part) >= len(node):
                return None
            node = node[int(part)]
        elif isinstance(node, dict) and part in node:
            node = node[part]
        else:
            return None
    return node


def lower_is_better(metric: str) -> bool:
    leaf = metric.rsplit(".", 1)[-1]
    return leaf.endswith(_LOWER_BETTER_SUFFIXES)


def parse_metric(spec: str):
    """``path`` or ``path:lower`` / ``path:higher`` -> (path, lower?)."""
    path, _, direction = spec.partition(":")
    if direction not in ("", "lower", "higher"):
        raise ValueError(f"bad metric direction {direction!r} in {spec!r} "
                         f"(want 'lower' or 'higher')")
    if direction:
        return path, direction == "lower"
    return path, lower_is_better(path)


def check_provenance(runs) -> list:
    """The refusal gate: every run pinned to a commit, all runs on one
    platform/device_kind. Returns failures (empty == comparable)."""
    failures = []
    for path, result in runs:
        prov = result.get("provenance")
        if not isinstance(prov, dict):
            failures.append(f"{path}: missing provenance block — "
                            f"an unstamped bench cannot be compared")
            continue
        if not prov.get("git_sha"):
            failures.append(f"{path}: provenance has no git_sha — "
                            f"cannot pin this run to a commit")
    if failures:
        return failures
    base_path, base = runs[0]
    for key in ("platform", "device_kind"):
        want = base["provenance"].get(key)
        for path, result in runs[1:]:
            got = result["provenance"].get(key)
            if got != want:
                failures.append(
                    f"provenance disagreement on {key}: {base_path} ran on "
                    f"{want!r} but {path} on {got!r} — cross-platform "
                    f"deltas are not regressions, refusing to compare")
    return failures


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("runs", nargs="+",
                    help="bench JSONs, oldest first, candidate last")
    ap.add_argument("--metric", action="append", default=[],
                    required=True,
                    help="dotted path into the result dict, optionally "
                         "suffixed :lower/:higher (which direction is "
                         "better); repeatable")
    ap.add_argument("--max-regress-pct", type=float, default=10.0,
                    metavar="N", help="fail on a regression worse than "
                                      "N%% first->last (default 10)")
    args = ap.parse_args(argv)
    if len(args.runs) < 2:
        ap.error("need at least two runs to compare")

    runs = []
    for path in args.runs:
        try:
            with open(path) as f:
                runs.append((path, json.load(f)))
        except (OSError, ValueError) as e:
            print(f"REFUSED: {path}: unreadable bench JSON ({e})",
                  file=sys.stderr)
            return 2

    prov_failures = check_provenance(runs)
    if prov_failures:
        for f in prov_failures:
            print(f"REFUSED: {f}", file=sys.stderr)
        return 2
    shas = [r["provenance"]["git_sha"] for _, r in runs]
    print(f"comparing {len(runs)} runs on "
          f"{runs[0][1]['provenance'].get('platform')}/"
          f"{runs[0][1]['provenance'].get('device_kind')}: "
          f"{' -> '.join(str(s)[:12] for s in shas)}")

    failures = []
    for spec in args.metric:
        try:
            metric, lower = parse_metric(spec)
        except ValueError as e:
            print(f"REFUSED: {e}", file=sys.stderr)
            return 2
        values = [(path, lookup(result, metric)) for path, result in runs]
        missing = [path for path, v in values
                   if not isinstance(v, (int, float)) or isinstance(v, bool)]
        if missing:
            failures.append(
                f"{metric}: missing/non-numeric in {missing}")
            continue
        first, last = float(values[0][1]), float(values[-1][1])
        if first == 0:
            failures.append(f"{metric}: baseline value is 0, no trend")
            continue
        # regression % is positive when the candidate moved the WRONG way
        change = (last - first) / abs(first) * 100.0
        regress = change if lower else -change
        trend = " -> ".join(f"{float(v):g}" for _, v in values)
        verdict = ("REGRESSED" if regress > args.max_regress_pct
                   else "ok")
        print(f"  {metric} [{'lower' if lower else 'higher'} is better]: "
              f"{trend}  ({change:+.1f}%)  {verdict}")
        if regress > args.max_regress_pct:
            failures.append(
                f"{metric}: {'+' if change > 0 else ''}{change:.1f}% "
                f"first->last exceeds the {args.max_regress_pct:g}% "
                f"budget ({'lower' if lower else 'higher'} is better)")

    for f in failures:
        print(f"FAIL: {f}", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
