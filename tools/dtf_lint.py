#!/usr/bin/env python
"""dtflint CLI — framework-aware static analysis for this repo.

Mechanically enforces the invariants the PR 1-6 review rounds caught by
hand (rule catalog + pre-fix examples: docs/static-analysis.md):

    host-sync-in-step    no float()/bool()/.item()/np.asarray()/
                         device_get on traced values in jit-reachable
                         step/decode functions (reachability follows
                         calls ACROSS modules via the v2 call graph)
    donation-after-use   never read a pytree a donate_argnums call
                         consumed (donating bindings resolve across
                         imports)
    lock-discipline      lock-guarded attributes only under the lock
    closed-vocab         flightrec kinds / waste causes / metric names
                         / the single ×3 MFU-multiplier site
    exception-hygiene    no bare except; no swallowed exceptions in the
                         retry/supervisor/checkpoint seams
    wall-clock-in-seam   no time.time()/unseeded randomness/os.urandom
                         in the deterministic seams (data/,
                         train/step.py, resilience/, test oracles)
    atomic-durable-write durable state (checkpoint/manifest/heartbeat/
                         quarantine paths) is written tmp+fsync+
                         os.replace, never truncated in place
    metric-naming        counters end _total, second-valued histograms
                         end _seconds, kinds match the docs tables
    shard-rules-coverage every partition_rules table compiles, ships a
                         coverage fixture, and is total with no dead
                         rules against it (first-match precedence)
    mesh-axis-closed-vocab  axis-name literals in PartitionSpec(...)
                         and collective axis args are in
                         parallel/mesh.AXIS_NAMES (no typo'd axes)
    sharding-seam-bypass NamedSharding/PartitionSpec constructed only
                         in parallel/sharding.py, rules tables, and
                         shard_map island layouts

Usage:
    tools/dtf_lint.py [--strict] [--json] [--rules a,b] PATH [PATH...]
    tools/dtf_lint.py --changed-only [--base REF] [--strict] PATH...
    tools/dtf_lint.py --list-rules
    tools/dtf_lint.py --self-check

Exit codes: 0 clean · 1 findings (or failed self-check) · 2 usage error.

``--strict`` additionally turns unparseable files into hard errors
(default: they are reported on stderr and skipped). ``--self-check``
proves every rule still fires on its shipped positive fixture, stays
quiet on the negative and suppressed ones, and — run before the tree
lint in tools/ci_fast.sh — keeps the gate from rotting silently.

``--changed-only`` reports findings only for .py files that differ
from ``--base`` (default HEAD: staged + unstaged + untracked). The
whole given tree is still PARSED — the v2 engine's cross-module
reachability and donator resolution need project scope — but output,
and the exit code, cover just the changed files, PLUS any findings
anchored outside the python set (the docs-table shape checks): a
docs-only edit re-lints, and docs drift is never filtered away. When
neither python nor docs changed the lint is skipped outright. The
full ``--strict`` tree lint in CI remains the authoritative gate.

Suppressions: ``# dtflint: disable=<rule>[,<rule>]`` on the flagged
line or the line above; ``# dtflint: disable-file=<rule>`` anywhere in
the file.
"""

import argparse
import importlib.util
import json
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _changed_files(base: str) -> set[str] | None:
    """Real paths of .py AND .md files that differ from ``base`` in the
    git repository enclosing the CURRENT directory (committed diff +
    working tree + untracked). Markdown counts because project-scope
    rules anchor findings in the docs tables (metric-naming's
    docs-side shape checks) — a docs-only change must not
    short-circuit the lint. None on git failure (caller reports a
    usage error)."""
    top = subprocess.run(["git", "rev-parse", "--show-toplevel"],
                         capture_output=True, text=True)
    if top.returncode != 0:
        print(f"dtf_lint.py: error: not inside a git repository: "
              f"{top.stderr.strip()}", file=sys.stderr)
        return None
    root = top.stdout.strip()
    changed: set[str] = set()
    cmds = (
        ["git", "diff", "--name-only", "--diff-filter=d", base, "--",
         "*.py", "*.md"],
        ["git", "ls-files", "--others", "--exclude-standard", "--",
         "*.py", "*.md"],
    )
    for cmd in cmds:
        proc = subprocess.run(cmd, cwd=root, capture_output=True,
                              text=True)
        if proc.returncode != 0:
            print(f"dtf_lint.py: error: {' '.join(cmd)} failed: "
                  f"{proc.stderr.strip()}", file=sys.stderr)
            return None
        for line in proc.stdout.splitlines():
            line = line.strip()
            if line:
                changed.add(os.path.realpath(os.path.join(root, line)))
    return changed


def _load_analysis():
    """Load distributed_tensorflow_tpu.analysis WITHOUT importing its
    parent package: the parent __init__ pulls the whole framework (jax,
    numpy, every submodule) and runs the chip-lock pin side effect —
    the analyzer itself is stdlib-only and must stay runnable on a box
    with neither accelerator stack installed. The package only uses
    intra-package relative imports, so it loads cleanly under an
    alias."""
    name = "dtf_analysis"
    if name in sys.modules:
        return sys.modules[name]
    pkg_dir = os.path.join(_REPO, "distributed_tensorflow_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir],
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="dtf_lint.py",
        description="framework-aware static analysis (dtflint)")
    ap.add_argument("paths", nargs="*", help="files or directories to lint")
    ap.add_argument("--strict", action="store_true",
                    help="treat unparseable files as errors")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings on stdout")
    ap.add_argument("--rules", default=None,
                    help="comma-separated subset of rules to run")
    ap.add_argument("--changed-only", action="store_true",
                    help="report findings only for files changed vs "
                         "--base (tree still parsed for cross-module "
                         "context)")
    ap.add_argument("--base", default="HEAD",
                    help="git ref --changed-only diffs against "
                         "(default: HEAD)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--self-check", action="store_true",
                    help="verify every rule fires on its shipped fixtures")
    args = ap.parse_args(argv)

    analysis = _load_analysis()
    RULES, lint_paths = analysis.RULES, analysis.lint_paths
    fixtures = analysis.fixtures

    if args.list_rules:
        for name in sorted(RULES):
            print(f"{name:20s} {RULES[name].summary}")
        return 0

    if args.self_check:
        failures = fixtures.self_check()
        for f in failures:
            print(f"SELF-CHECK FAIL: {f}", file=sys.stderr)
        if not failures:
            print(f"dtflint self-check OK: {len(RULES)} rules × "
                  f"positive/negative/suppressed fixtures", file=sys.stderr)
        return 1 if failures else 0

    if not args.paths:
        ap.print_usage(sys.stderr)
        print("dtf_lint.py: error: no paths given "
              "(or use --list-rules / --self-check)", file=sys.stderr)
        return 2

    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]

    changed: set[str] | None = None
    if args.changed_only:
        changed = _changed_files(args.base)
        if changed is None:
            return 2
        if not changed:
            print(f"dtflint: no python/docs files changed vs "
                  f"{args.base}; nothing to lint", file=sys.stderr)
            return 0

    parse_errors: list[str] = []

    def on_parse_error(path, exc):
        parse_errors.append(f"{path}: syntax error: {exc}")

    try:
        findings = lint_paths(args.paths, rules=rules,
                              on_parse_error=on_parse_error)
    except (FileNotFoundError, KeyError) as e:
        print(f"dtf_lint.py: error: {e}", file=sys.stderr)
        return 2

    if changed is not None:
        # findings anchored OUTSIDE the linted python set (the docs
        # tables) always pass through — filtering them would approve
        # exactly the vocabulary drift the docs-side checks block
        findings = [f for f in findings
                    if not f.path.endswith(".py")
                    or os.path.realpath(f.path) in changed]
        parse_errors = [
            e for e in parse_errors
            if os.path.realpath(e.split(":", 1)[0]) in changed
        ]

    for err in parse_errors:
        print(err, file=sys.stderr)
    if args.as_json:
        print(json.dumps([f.to_json() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.format())
        if findings:
            by_rule: dict[str, int] = {}
            for f in findings:
                by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
            summary = ", ".join(f"{n} {r}" for r, n in sorted(by_rule.items()))
            print(f"dtflint: {len(findings)} finding(s): {summary}",
                  file=sys.stderr)
        else:
            print("dtflint: clean", file=sys.stderr)
    if args.strict and parse_errors:
        return 1
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main())
