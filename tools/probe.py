#!/usr/bin/env python
"""Canonical relay-health probe: the ONLY sanctioned way to ask "is the
tunneled TPU up?" outside a chip session.

Why a tool instead of `python -c "import jax; jax.devices()"`:

- A bare device init CONTENDS for the single tunneled lease if a chip
  session is live (the round-3 collision that cost the BERT/GPT suite).
  This tool refuses to probe while the session flock is held.
- Every verdict lands in the shared probe cache
  (utils/benchmarking.write_probe_cache), so the driver-invoked bench
  and sibling tools reuse it instead of re-deriving relay state with
  their own 90-150 s hangs (VERDICT r4 item 3).
- The probe runs device init in a subprocess under a hard timeout —
  backend init blocks forever when the relay is down.

Exit codes: 0 healthy, 1 down/hung, 2 skipped (chip session live).
Usage: python tools/probe.py [timeout_s]   (default 90, the budget every
call site and the cache-TTL arithmetic standardize on; healthy init is
16-20 s measured)
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    timeout_s = float(sys.argv[1]) if len(sys.argv) > 1 else 90.0

    from distributed_tensorflow_tpu.utils import benchmarking as bm
    from distributed_tensorflow_tpu.utils import chip_lock

    holder = chip_lock.lock_holder()
    if holder is not None:
        print(f"SKIP: chip session live (pid {holder}); not probing",
              file=sys.stderr)
        return 2

    # Payload AND retry policy are the bench ladder's own
    # (benchmarking.probe_with_retry): one definition of "healthy" and
    # one one-slow-probe rule, so the cache semantics cannot drift
    # between the watcher's probes and the harnesses'.
    healthy = bm.probe_with_retry(
        timeout_s, log=lambda s: print(s, file=sys.stderr))
    bm.write_probe_cache(healthy, source="tools/probe.py")
    print("HEALTHY" if healthy else "DOWN")
    return 0 if healthy else 1


if __name__ == "__main__":
    sys.exit(main())
