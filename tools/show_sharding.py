#!/usr/bin/env python
"""Print a workload's parameter sharding plan — path, shape, dtype,
PartitionSpec, and bytes per device — without materializing anything
(jax.eval_shape only).

The reference's placement was implicit and invisible (round-robin over
PS tasks inside ``replica_device_setter``, $TF device_setter.py:147-149
— you found out where a variable lived by crashing); here placement is
declarative, so it can be shown before running. Uses the same fake-CPU
mesh rig as the tests.

Usage:
  tools/show_sharding.py <workload> [--rules] [--mesh.data=2 ...]
e.g.
  tools/show_sharding.py bert_pretrain --mesh.data=2 --mesh.fsdp=2 \
      --mesh.model=2

``--rules`` switches to the partition-rules attribution view: one line
per param naming the table row that won it (rule index, regex,
resulting spec) plus a DEAD trailer for rows that matched nothing — the
debugging handle for shard-rules-coverage / PartitionCoverageError
failures. Params the table misses print as UNMATCHED instead of
raising, so a broken table is still inspectable.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"


def _fake_device_count() -> int:
    """Size the fake CPU mesh from the --mesh.* overrides: the product of
    fixed axes must equal the device count (exactly, unless a -1 wildcard
    absorbs a remainder)."""
    product, wildcard = 1, False
    for a in sys.argv[2:]:
        if a.startswith("--mesh.") and "=" in a:
            v = a.split("=", 1)[1]
            try:
                n = int(v)
            except ValueError:
                continue
            if n == -1:
                wildcard = True
            elif n > 0:
                product *= n
    if wildcard:
        # the wildcard axis absorbs the remainder, but the device count
        # must stay divisible by the fixed-axis product (e.g. pipe=3
        # data=-1 needs 9 devices, not max(8,3)=8 which 3 won't divide)
        count = max(8, product)
        return count if count % product == 0 else ((count // product) + 1) * product
    return product if product > 1 else 8


_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags
        + f" --xla_force_host_platform_device_count={_fake_device_count()}"
    ).strip()
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np


def main() -> None:
    if len(sys.argv) < 2 or sys.argv[1].startswith("-"):
        raise SystemExit(__doc__)
    workload = sys.argv[1]
    rules_view = "--rules" in sys.argv[2:]
    overrides = [a for a in sys.argv[2:] if a != "--rules"]

    from distributed_tensorflow_tpu.parallel import build_mesh, describe
    from distributed_tensorflow_tpu.parallel import sharding as sh
    from distributed_tensorflow_tpu.train import make_optimizer
    from distributed_tensorflow_tpu.utils import config as config_lib
    from distributed_tensorflow_tpu import workloads

    mod = workloads.get(workload)
    cfg = config_lib.apply_overrides(mod.default_config(), overrides)
    mesh = build_mesh(cfg.mesh)
    parts = mod.build(cfg, mesh)
    tx = parts.tx if parts.tx is not None else make_optimizer(cfg.optimizer)

    abstract_params, _ = jax.eval_shape(
        parts.init_fn, jax.random.PRNGKey(0)
    )

    if rules_view:
        print(f"workload: {workload}   mesh: {describe(mesh)}")
        if parts.param_rules is None:
            what = ("an explicit param_specs tree"
                    if parts.param_specs is not None
                    else "no rules (fully replicated"
                    + (" before auto-FSDP)" if parts.fsdp else ")"))
            raise SystemExit(
                f"show_sharding --rules: workload {workload!r} uses "
                f"{what}; there is no rules table to attribute")
        table = parts.param_rules
        if not isinstance(table, sh.PartitionRules):
            # legacy path-rules sequence: wrap for the same listing
            table = sh.PartitionRules(
                "<legacy-path-rules>",
                tuple(sh.PartitionRow(p, s) for p, s in parts.param_rules),
            )
        matches = sh.attribute_partition_rules(table, abstract_params)
        print(sh.format_attribution(table, matches))
        if parts.fsdp:
            print("(fsdp=True: replicated leaves above are then offered "
                  "to auto_fsdp_specs — run without --rules for the "
                  "final merged layout)")
        _ = tx
        return

    if parts.param_specs is not None:
        # explicit spec tree (pipelined stacked layouts) wins, same
        # precedence as init_train_state
        specs = parts.param_specs
    elif parts.param_rules is not None:
        # tables resolve strictly (coverage contract), legacy path
        # rules keep replicate-on-miss — same seam as init_train_state
        specs = sh.specs_from_rules(abstract_params, parts.param_rules)
    else:
        specs = sh.replicated_specs(abstract_params)
    if parts.param_specs is None and parts.fsdp:
        # same merge as train/step.init_train_state: rules win, auto-FSDP
        # fills the replicated remainder
        specs = sh.merge_specs(
            specs, sh.auto_fsdp_specs(abstract_params, mesh))

    print(f"workload: {workload}   mesh: {describe(mesh)}")
    axis_size = dict(mesh.shape)
    rows, total, total_dev = [], 0, 0
    for (path, leaf), (_, spec) in zip(
        jax.tree_util.tree_leaves_with_path(abstract_params),
        jax.tree_util.tree_leaves_with_path(specs),
    ):
        name = jax.tree_util.keystr(path)
        nbytes = int(np.prod(leaf.shape) * leaf.dtype.itemsize)
        shards = 1
        for entry in spec:
            for ax in ([entry] if isinstance(entry, str) else (entry or ())):
                shards *= axis_size.get(ax, 1)
        rows.append((name, leaf.shape, str(leaf.dtype),
                     str(spec), nbytes // shards))
        total += nbytes
        total_dev += nbytes // shards
    w = max(len(r[0]) for r in rows)
    ws = max(len(r[3]) for r in rows)
    print(f"{'param':{w}s}  {'shape':>18s} {'dtype':>9s}  "
          f"{'spec':{ws}s} {'bytes/device':>14s}")
    for name, shape, dtype, spec, per_dev in rows:
        print(f"{name:{w}s}  {str(shape):>18s} {dtype:>9s}  "
              f"{spec:{ws}s} {per_dev:14,d}")
    print(f"\nparams total: {total:,} bytes replicated-equivalent; "
          f"{total_dev:,} bytes/device after sharding "
          f"({total / max(total_dev, 1):.2f}x reduction)")
    print("optimizer state inherits the same specs per-leaf "
          "(train/step.py opt-state spec inheritance)")
    _ = tx  # built to validate the config resolves; state not needed


if __name__ == "__main__":
    main()
