#!/bin/bash
# HISTORICAL (round-3b record; superseded by tools/onchip_round5.sh —
# new sessions go there, scaling curves through tools/sweep.py).
# Round-3 FOLLOW-UP on-chip session — run after onchip_round3.sh landed
# the first measurements and the builder fixed what they exposed:
#   - bench_hbm now measures + subtracts the tunnel dispatch RTT (the
#     first run's 43.5 "TFLOP/s" was ~80 ms of RTT folded into a 4-iter
#     chain) and adds a host->device transfer bandwidth row (the
#     fed-window denominator).
#   - The fused conv+BN / ln_matmul composites keep their Pallas forward
#     (measured 1.0-2.5x over XLA) but default to the XLA backward
#     (measured: the two-pass Pallas backward is 0.40-0.87x of XLA).
#   - validate_fused_tpu gained a bench-shape compile/execute sweep (the
#     r3 dw-kernel VMEM OOM shapes, caught only at batch-256 shapes).
#   - bert/bert_dense_attn re-run: the first session's rows are CPU
#     fallbacks (a concurrent builder process contended for the single
#     device lease during the probe — operator error, see PERF_NOTES).
# IMPORTANT: nothing else may touch JAX while this runs (single lease).
# Usage: bash tools/onchip_round3b.sh [outdir]   (default /tmp/onchip_r3b)
set -u
cd "$(dirname "$0")/.."
OUT=$(readlink -f "${1:-/tmp/onchip_r3b}")  # absolute: redirects below
mkdir -p "$OUT"                             # must survive any later cd

ART="artifacts/onchip_r3"  # in-tree; script cd'd to the repo root
mkdir -p "$ART"

run() { # name timeout_s cmd...
  local name=$1 t=$2; shift 2
  echo "=== $name ($(date -u +%H:%M:%S)) ==="
  timeout --signal=TERM --kill-after=60 "$t" "$@" \
    >"$OUT/$name.log" 2>&1
  local rc=$?
  echo "    rc=$rc  tail:"
  tail -3 "$OUT/$name.log" | sed 's/^/    /'
  # preserve in-tree IMMEDIATELY: the round may end (or the relay die)
  # mid-session, and only committed files survive
  cp "$OUT/$name.log" "$ART/${name}_r3b.log" 2>/dev/null
  return $rc
}

run probe 180 python -u -c "
import jax, jax.numpy as jnp
print(jax.devices(), float(jax.jit(lambda a:(a@a).sum())(jnp.ones((256,256),jnp.bfloat16))))
" || { echo 'relay down; aborting session'; exit 1; }

# Ordered by value-per-minute: the window has died mid-session twice,
# so the headline number and the roofline inputs go FIRST (bench_auto
# self-protects: probe, per-impl try/except, standard fallback; it does
# not need the validator as a gate).

# 1. corrected roofline: RTT-subtracted HBM/MXU + host->device bandwidth
run hbm 900 env HBM_ITERS=64 python -u tools/bench_hbm.py

# 2. flagship bench — unpinned: A/Bs fused-vs-standard and reports the
#    faster (the driver's end-of-round behavior)
run bench_auto 1800 python -u bench.py
# stamp the headline row in-tree NOW (not at session end): a mid-session
# relay death or round end must not cost the round its TPU number
LATEST=$(grep -h '"metric"' "$OUT"/bench_auto.log 2>/dev/null | tail -1)
[ -n "$LATEST" ] && printf '%s\n' "$LATEST" > "$ART"/BENCH_LATEST.json

# 3. validator incl. the bench-shape compile/execute sweep
run validate 1500 python -u tools/validate_fused_tpu.py

# 4. pinned A/B rows so each label is guaranteed to mean what it says
run bench_fused_xlabwd 1200 env BENCH_BLOCK_IMPL=fused python -u bench.py
run bench_fused_pallasbwd 1200 env BENCH_BLOCK_IMPL=fused \
  DTF_FUSED_BWD=pallas python -u bench.py
run bench_standard 1200 env BENCH_BLOCK_IMPL=standard python -u bench.py

# 5. the BERT/GPT suite the r3a session lost to the lease collision
run bert 1200 python -u tools/bench_bert.py
run bert_wide_flash 1200 env DTF_FLASH_BLOCK_Q=256 DTF_FLASH_BLOCK_K=512 \
  python -u tools/bench_bert.py
run bert_dense_attn 1200 env BENCH_ATTN=dense python -u tools/bench_bert.py
run gpt_plain 1200 env BENCH_MODEL=gpt python -u tools/bench_bert.py
run gpt_fused_ln 1200 env BENCH_MODEL=gpt BENCH_FUSED_LN=1 \
  python -u tools/bench_bert.py
run gpt_long4k 1500 env BENCH_MODEL=gpt BENCH_SEQ=4096 BENCH_BATCH=8 \
  BENCH_REMAT=1 python -u tools/bench_bert.py

# 6. profile capture at bench config (fused fwd + XLA bwd): the XPlane
#    trace that round-4 tuning reads. ~30 profiled steps, batch 256.
rm -rf "$OUT/profile"   # never tar a stale prior session's trace
run profile 1200 python -u examples/train.py resnet50_imagenet \
  --train.num_steps=30 --train.profile=true \
  --train.profile_dir="$OUT/profile" \
  --model.norm_dtype=bfloat16 --model.stem=space_to_depth \
  --model.block_impl=fused --data.global_batch_size=256 \
  --data.image_size=224 --checkpoint.directory= \
  --train.log_every=10
tar -C "$OUT" -czf "$OUT/profile.tgz" profile 2>/dev/null \
  && echo "    profile.tgz $(du -h "$OUT/profile.tgz" | cut -f1)"

# 7. LAST (can stall, r3a microbench_grad rc=124): AOT-compile the
#    non-default Pallas backward at every bench shape — "only" mode
#    skips the parity suite + default sweep step 2 already ran
run validate_pallas_bwd 1200 env VALIDATE_PALLAS_BWD=only \
  python -u tools/validate_fused_tpu.py

echo "=== session done; JSON lines: ==="
grep -h '"metric"' "$OUT"/hbm.log "$OUT"/bench_*.log "$OUT"/bert*.log \
  "$OUT"/gpt*.log 2>/dev/null
echo "logs in $OUT"

for f in "$OUT"/*.log; do
  cp "$f" "$ART/$(basename "$f" .log)_r3b.log" 2>/dev/null
done
cp "$OUT/profile.tgz" "$ART/profile_r3b.tgz" 2>/dev/null || true
# only replace the preserved BENCH_LATEST.json when this session actually
# produced a metric row (a truncating redirect would destroy the r3a row
# exactly when the window dies early — the failure mode we're hedging)
LATEST=$(grep -h '"metric"' "$OUT"/bench_auto.log 2>/dev/null | tail -1)
[ -n "$LATEST" ] && printf '%s\n' "$LATEST" > "$ART"/BENCH_LATEST.json
echo "artifacts copied to $ART"
