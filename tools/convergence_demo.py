#!/usr/bin/env python
"""Convergence demonstration on REAL decoded JPEG data (VERDICT r2 item 4).

Pushes a real image-classification dataset through the framework's whole
production path: JPEG record files -> JpegClassificationDataset decode +
augment -> examples/train.py-equivalent run (Trainer, checkpoints,
TensorBoard events) -> standalone eval from the checkpoint.

Data: scikit-learn's bundled `load_digits` (1,797 real 8x8 handwritten
digit scans — the only real image dataset available in this zero-egress
image). Images are upscaled to 32x32 RGB and JPEG-encoded; a 1500/297
train/eval split keeps eval held out. The CNN family (cifar10_cnn
workload) trains on the decoded stream. Chance is 10%; the committed gate
asserts >=90% held-out top-1, demonstrating the BASELINE.json:2 top-1
machinery end to end (decode, augment, train, checkpoint, restore, eval).

Usage:  python tools/convergence_demo.py [--steps N] [--workdir DIR]
Prints one JSON line: {"train_acc":..,"eval_top1":..,"steps":..}.
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def build_records(workdir: str) -> tuple[str, str]:
    import numpy as np
    from PIL import Image
    from sklearn.datasets import load_digits

    from distributed_tensorflow_tpu.data.jpeg_records import (
        make_jpeg_record_file,
    )

    digits = load_digits()
    imgs8 = (digits.images / 16.0 * 255.0).astype(np.uint8)  # [N, 8, 8]
    rng = np.random.RandomState(0)
    order = rng.permutation(len(imgs8))
    imgs8, labels = imgs8[order], digits.target[order]

    def upscale(batch):
        out = np.empty((len(batch), 32, 32, 3), np.uint8)
        for i, im in enumerate(batch):
            big = np.asarray(
                Image.fromarray(im, "L").resize((32, 32), Image.BILINEAR)
            )
            out[i] = big[..., None].repeat(3, axis=-1)
        return out

    n_train = 1500
    train = os.path.join(workdir, "digits_train")
    evalp = os.path.join(workdir, "digits_eval")
    make_jpeg_record_file(train, upscale(imgs8[:n_train]), labels[:n_train])
    make_jpeg_record_file(evalp, upscale(imgs8[n_train:]), labels[n_train:])
    print(f"records: {n_train} train / {len(imgs8) - n_train} eval "
          f"real digit scans -> {workdir}", file=sys.stderr)
    return train, evalp


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=400)
    ap.add_argument("--workdir", default="/tmp/convergence_demo")
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--min-top1", type=float, default=0.9,
                    help="held-out accuracy gate (lower it for smoke runs)")
    args = ap.parse_args()

    os.makedirs(args.workdir, exist_ok=True)
    train_rec, eval_rec = build_records(args.workdir)

    from distributed_tensorflow_tpu import workloads

    ckdir = os.path.join(args.workdir, "ck")
    common = [
        f"--data.image_size=32", "--data.channels=3",
        "--data.num_classes=10",
        f"--data.global_batch_size={args.batch}",
        "--mesh.data=-1",
    ]
    log_every = max(1, min(50, args.steps // 4))
    result = workloads.run_workload("cifar10_cnn", [
        f"--data.dataset=jpeg:{train_rec}",
        f"--train.num_steps={args.steps}",
        f"--train.log_every={log_every}",
        f"--optimizer.total_steps={args.steps}",
        "--optimizer.learning_rate=0.02",
        f"--checkpoint.directory={ckdir}",
        "--train.eval_batches=2",
        *common,
    ])
    train_acc = float(result.history[-1].get("accuracy", 0.0))

    # standalone eval from the checkpoint on the HELD-OUT record pair —
    # the examples/eval.py path
    eval_metrics = workloads.eval_workload("cifar10_cnn", [
        f"--data.dataset=jpeg:{eval_rec}",
        f"--checkpoint.directory={ckdir}",
        "--train.eval_batches=2",
        *common,
    ])
    top1 = float(eval_metrics.get("accuracy", 0.0))
    print(json.dumps({
        "train_acc": round(train_acc, 4),
        "eval_top1": round(top1, 4),
        "steps": args.steps,
        "dataset": "sklearn load_digits (real scans), 1500/297 split",
    }))
    if top1 < args.min_top1:
        raise SystemExit(
            f"held-out top-1 {top1:.3f} < {args.min_top1} gate")


if __name__ == "__main__":
    main()
