#!/usr/bin/env python
"""Telemetry smoke gate — seconds, not minutes (tools/ci_fast.sh tier).

Registers one metric of every kind, exercises span tracing and the
JSONL logger, renders Prometheus text exposition, and lints the output
against the exposition-format grammar with a regex — so a formatting
regression (bad label escaping, non-cumulative buckets, missing
``_sum``/``_count``) fails loudly before anything tries to scrape a
real run. No device, no model: the obs layer is plain host code.

Usage:
    python tools/obs_check.py
"""

import json
import re
import sys
import tempfile

sys.path.insert(0, __file__.rsplit("/", 2)[0])

# Prometheus text-exposition grammar (version 0.0.4), line-by-line.
_METRIC_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_LABELS = r"\{[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\"(?:,[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\")*\}"
_VALUE = r"(?:[-+]?(?:[0-9]*\.)?[0-9]+(?:[eE][-+]?[0-9]+)?|[-+]?Inf|NaN)"
LINE_RE = re.compile(
    r"^(?:"
    r"# HELP " + _METRIC_NAME + r" .*"
    r"|# TYPE " + _METRIC_NAME + r" (?:counter|gauge|histogram|summary|untyped)"
    r"|" + _METRIC_NAME + r"(?:" + _LABELS + r")? " + _VALUE + r"(?: [0-9]+)?"
    r")$"
)


def check(verbose: bool = True) -> list[str]:
    """Returns a list of failures (empty == pass)."""
    from distributed_tensorflow_tpu import obs

    failures: list[str] = []
    reg = obs.Registry()

    # one of each kind, with and without labels
    reg.counter("obs_check_events_total", "smoke events").inc(3)
    reg.gauge("obs_check_occupancy", "smoke gauge").set(0.75)
    h = reg.histogram("obs_check_latency_seconds", "smoke latency")
    for v in (1e-4, 3e-3, 3e-3, 0.2, 5.0, 1e4):  # incl. overflow bucket
        h.observe(v)
    reg.counter("obs_check_finished_total", "by reason", reason="eos").inc()
    reg.counter("obs_check_finished_total", "by reason",
                reason='max"len\\path').inc()  # escaping torture

    tracer = obs.Tracer(registry=reg, annotate=False)
    with tracer.span("check"):
        with tracer.span("inner"):
            pass
    if [s.path for s in tracer.events] != ["check.inner", "check"]:
        failures.append(f"tracer span paths wrong: {list(tracer.events)}")

    text = obs.render(reg)
    for i, line in enumerate(text.splitlines(), 1):
        if not LINE_RE.match(line):
            failures.append(f"line {i} fails exposition lint: {line!r}")

    # cumulative-bucket + count/sum invariants
    hist_count = h.count
    last_bucket = max(
        int(m.group(1))
        for m in re.finditer(
            r'obs_check_latency_seconds_bucket\{le="\+Inf"\} (\d+)', text
        )
    )
    if last_bucket != hist_count:
        failures.append(
            f"+Inf bucket {last_bucket} != histogram count {hist_count}"
        )

    # JSONL round-trip
    with tempfile.NamedTemporaryFile("r", suffix=".jsonl") as tmp:
        with obs.JsonlLogger(tmp.name, reg, chief_only=False) as jl:
            jl.event("smoke", answer=42)
            jl.write_snapshot(tag="check")
        recs = [json.loads(line) for line in open(tmp.name)]
        if len(recs) != 2 or recs[0]["answer"] != 42:
            failures.append(f"jsonl round-trip wrong: {recs}")
        snap = recs[1]["metrics"]
        if snap["obs_check_events_total"]["value"] != 3:
            failures.append(f"snapshot counter wrong: {snap}")

    if verbose:
        print(text, end="")
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        if not failures:
            print(f"OK: {len(text.splitlines())} exposition lines, "
                  f"{len(reg.collect())} metrics, jsonl round-trip clean",
                  file=sys.stderr)
    return failures


def main() -> int:
    return 1 if check() else 0


if __name__ == "__main__":
    raise SystemExit(main())
