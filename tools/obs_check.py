#!/usr/bin/env python
"""Telemetry smoke gate — seconds, not minutes (tools/ci_fast.sh tier).

Registers one metric of every kind, exercises span tracing and the
JSONL logger, renders Prometheus text exposition, and lints the output
against the exposition-format grammar with a regex — so a formatting
regression (bad label escaping, non-cumulative buckets, missing
``_sum``/``_count``) fails loudly before anything tries to scrape a
real run. Also gates the flight-recorder dump schema (required keys,
monotonic timestamps, known event kinds, ring-overflow accounting —
obs/flightrec.py) and the goodput/MFU surface (``goodput_fraction`` /
``mfu`` gauges, ``wasted_seconds_total{cause}`` counters, the shared
percentile read-back — obs/goodput.py). No device, no model: the obs
layer is plain host code.

Usage:
    python tools/obs_check.py
"""

import json
import re
import sys
import tempfile

sys.path.insert(0, __file__.rsplit("/", 2)[0])

# Prometheus text-exposition grammar (version 0.0.4), line-by-line.
_METRIC_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_LABELS = r"\{[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\"(?:,[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\")*\}"
_VALUE = r"(?:[-+]?(?:[0-9]*\.)?[0-9]+(?:[eE][-+]?[0-9]+)?|[-+]?Inf|NaN)"
LINE_RE = re.compile(
    r"^(?:"
    r"# HELP " + _METRIC_NAME + r" .*"
    r"|# TYPE " + _METRIC_NAME + r" (?:counter|gauge|histogram|summary|untyped)"
    r"|" + _METRIC_NAME + r"(?:" + _LABELS + r")? " + _VALUE + r"(?: [0-9]+)?"
    r")$"
)


def check(verbose: bool = True) -> list[str]:
    """Returns a list of failures (empty == pass)."""
    from distributed_tensorflow_tpu import obs

    failures: list[str] = []
    reg = obs.Registry()

    # one of each kind, with and without labels
    reg.counter("obs_check_events_total", "smoke events").inc(3)
    reg.gauge("obs_check_occupancy", "smoke gauge").set(0.75)
    h = reg.histogram("obs_check_latency_seconds", "smoke latency")
    for v in (1e-4, 3e-3, 3e-3, 0.2, 5.0, 1e4):  # incl. overflow bucket
        h.observe(v)
    reg.counter("obs_check_finished_total", "by reason", reason="eos").inc()
    reg.counter("obs_check_finished_total", "by reason",
                reason='max"len\\path').inc()  # escaping torture

    tracer = obs.Tracer(registry=reg, annotate=False)
    with tracer.span("check"):
        with tracer.span("inner"):
            pass
    if [s.path for s in tracer.events] != ["check.inner", "check"]:
        failures.append(f"tracer span paths wrong: {list(tracer.events)}")

    text = obs.render(reg)
    for i, line in enumerate(text.splitlines(), 1):
        if not LINE_RE.match(line):
            failures.append(f"line {i} fails exposition lint: {line!r}")

    # cumulative-bucket + count/sum invariants
    hist_count = h.count
    last_bucket = max(
        int(m.group(1))
        for m in re.finditer(
            r'obs_check_latency_seconds_bucket\{le="\+Inf"\} (\d+)', text
        )
    )
    if last_bucket != hist_count:
        failures.append(
            f"+Inf bucket {last_bucket} != histogram count {hist_count}"
        )

    # JSONL round-trip
    with tempfile.NamedTemporaryFile("r", suffix=".jsonl") as tmp:
        with obs.JsonlLogger(tmp.name, reg, chief_only=False) as jl:
            jl.event("smoke", answer=42)
            jl.write_snapshot(tag="check")
        recs = [json.loads(line) for line in open(tmp.name)]
        if len(recs) != 2 or recs[0]["answer"] != 42:
            failures.append(f"jsonl round-trip wrong: {recs}")
        snap = recs[1]["metrics"]
        if snap["obs_check_events_total"]["value"] != 3:
            failures.append(f"snapshot counter wrong: {snap}")

    failures += _check_flightrec()
    failures += _check_goodput(reg)
    failures += _check_scaling()
    failures += _check_fleetview()
    failures += _check_reqtrace()

    if verbose:
        print(text, end="")
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        if not failures:
            print(f"OK: {len(text.splitlines())} exposition lines, "
                  f"{len(reg.collect())} metrics, jsonl round-trip clean",
                  file=sys.stderr)
    return failures


def _check_flightrec() -> list[str]:
    """Flight-recorder gate: emit through a small ring, dump, and push
    the dump through the same schema validator tools/postmortem.py and
    CI use — plus negative cases the validator must catch."""
    import os

    from distributed_tensorflow_tpu.obs import flightrec as fr

    failures: list[str] = []
    rec = fr.FlightRecorder(capacity=4)
    rec.emit("train_start", step=0)
    rec.emit("fault_fired", step=3, fault="sigterm")
    rec.emit("ckpt_save", step=4, trigger="preemption")
    rec.emit("sup_restart", restart=1, cause="preemption")
    rec.emit("ckpt_restore", step=2, fallback=True)
    rec.emit("train_stop", step=8, reason="num_steps=8")
    if len(rec) != 4 or rec.dropped != 2:
        failures.append(
            f"ring overflow wrong: len={len(rec)} dropped={rec.dropped} "
            f"(want 4/2)")
    try:
        # deliberate negative: the closed vocabulary must reject this
        rec.emit("not_a_kind")  # dtflint: disable=closed-vocab
        failures.append("emit accepted an unknown event kind")
    except ValueError:
        pass

    with tempfile.TemporaryDirectory(prefix="obs_check_fr_") as d:
        path = rec.dump(os.path.join(d, "pm.jsonl"), reason="obs_check")
        for f in fr.validate_dump(path):
            failures.append(f"flightrec dump invalid: {f}")
        if not fr.contains_in_order(
                rec.events(),
                [("sup_restart", {}), ("ckpt_restore", {"fallback": True})]):
            failures.append("contains_in_order missed a present sequence")
        if fr.contains_in_order(
                rec.events(), [("ckpt_restore", {}), ("sup_restart", {})]):
            failures.append("contains_in_order accepted a reversed sequence")
        # the validator must catch what emit() can never produce: an
        # unknown kind, a decreasing timestamp, a key-less record
        bad = os.path.join(d, "bad.jsonl")
        with open(path) as f_in:
            lines = f_in.read().splitlines()
        # reviewed: scratch corpus for the validator's must-fail probes,
        # torn-on-crash is irrelevant (the file exists only inside this
        # check's tempdir)
        with open(bad, "w") as f_out:  # dtflint: disable=atomic-durable-write
            f_out.write(lines[0] + "\n")
            f_out.write('{"t": 5.0, "kind": "meteor_strike"}\n')
            f_out.write('{"t": 4.0, "kind": "train_start"}\n')
            f_out.write('{"kind": "train_stop"}\n')
            f_out.write('{"t": 6.0, "kind": "train_stop", "step": "x"}\n')
            # a 5th event under a header claiming 4: count mismatch
            f_out.write('{"t": 7.0, "kind": "train_stop"}\n')
        bad_failures = fr.validate_dump(bad)
        for needle in ("unknown event kind", "decreases",
                       "missing/non-numeric", "non-int step",
                       "events, dump has"):
            if not any(needle in b for b in bad_failures):
                failures.append(
                    f"validator missed a '{needle}' violation: "
                    f"{bad_failures}")
    return failures


def _check_goodput(reg) -> list[str]:
    """Goodput/MFU gate: the gauge names the docs promise exist with the
    arithmetic they promise, device-free (peak/chips passed in)."""
    from distributed_tensorflow_tpu.obs import goodput

    failures: list[str] = []
    goodput.note_productive(3.0, registry=reg)
    goodput.note_wasted(goodput.WASTE_COMPILE_WARMUP, 0.5, registry=reg)
    goodput.note_wasted(goodput.WASTE_RETRY_BACKOFF, 0.25, registry=reg)
    goodput.note_wasted(goodput.WASTE_RESTART_RECOVERY, 0.25, registry=reg)
    frac = reg.get(goodput.GOODPUT_FRACTION)
    if frac is None or abs(frac.value - 0.75) > 1e-9:
        failures.append(f"goodput_fraction gauge wrong: "
                        f"{frac and frac.value} (want 0.75)")
    if abs(goodput.goodput_fraction(reg) - 0.75) > 1e-9:
        failures.append("goodput_fraction() read-back disagrees with gauge")
    for cause in goodput.WASTE_CAUSES:
        if reg.get(goodput.WASTED_SECONDS, cause=cause) is None:
            failures.append(f"missing wasted_seconds_total{{cause={cause}}}")
    try:
        # deliberate negative: the cause vocabulary must reject this
        goodput.note_wasted("procrastination", 1.0, registry=reg)  # dtflint: disable=closed-vocab
        failures.append("note_wasted accepted an unknown cause")
    except ValueError:
        pass
    # fwd 1e12 FLOPs/step × ×3 training multiplier × 1.5 steps/s over
    # 3 chips × 1e12 peak → MFU 1.5 exactly, published as the gauge
    mfu = goodput.train_mfu(1e12, 1.5, n_chips=3, peak_per_chip=1e12,
                            registry=reg)
    gauge = reg.get(goodput.MFU)
    if gauge is None or abs(gauge.value - mfu) > 1e-12 or abs(mfu - 1.5) > 1e-9:
        failures.append(f"mfu gauge/return mismatch: gauge="
                        f"{gauge and gauge.value} returned={mfu} (want 1.5)")
    # shared percentile read-back == the histogram's own percentile()
    h = reg.get("obs_check_latency_seconds")
    ms = goodput.latency_percentiles_ms(reg, "obs_check_latency_seconds")
    if abs(ms["p50_ms"] - round(float(h.percentile(0.5)) * 1e3, 3)) > 1e-9:
        failures.append(f"latency_percentiles_ms disagrees with "
                        f"Histogram.percentile: {ms}")
    return failures


def _check_scaling() -> list[str]:
    """Scaling-report gate (obs/scaling.py): a hand-built minimal
    ``dtf-scaling-1`` report must validate, and the must-fail cases —
    wrong schema tag, provenance-free cell, the CPU-masquerade
    (cell platform disagreeing with the header), non-positive
    throughput, mesh/device mismatch, an inconsistent gate — must each
    be caught. Pure dict work: no device, no jax."""
    import copy

    from distributed_tensorflow_tpu.obs import scaling

    failures: list[str] = []
    prov = {
        "backend": "cpu", "platform": "cpu", "device_kind": "cpu",
        "device_count": 8, "hostname": "ci", "git_sha": "deadbeef",
    }
    cell = {
        "cell": "dp8", "workload": "mlp", "axis": "dp", "n_devices": 8,
        "mesh": {"pipe": 1, "data": 8, "fsdp": 1, "seq": 1, "expert": 1,
                 "model": 1},
        "global_batch": 1024, "steps": 8, "steps_per_sec": 40.0,
        "examples_per_sec": 40960.0,
        "provenance": dict(prov),
    }
    base = {
        "cell": "1dev", "workload": "mlp", "axis": "dp", "n_devices": 1,
        "mesh": {"pipe": 1, "data": 1, "fsdp": 1, "seq": 1, "expert": 1,
                 "model": 1},
        "global_batch": 128, "steps": 8, "steps_per_sec": 120.0,
        "examples_per_sec": 15360.0,
        "provenance": dict(prov),
    }
    good = {
        "schema": scaling.SCHEMA,
        "provenance": dict(prov),
        "cells": [base, cell],
        "efficiency": scaling.scaling_efficiency([base, cell]),
        "gates": [{"gate": "mlp/dp8", "axis": "dp", "threshold": 0.8,
                   "value": 2.6667, "passed": True}],
    }
    got = scaling.validate_scaling_report(good)
    if got:
        failures.append(f"valid scaling report rejected: {got}")
    eff = good["efficiency"]
    if len(eff) != 1 or eff[0]["basis"] != "shared_host" \
            or abs(eff[0]["value"] - 40960.0 / 15360.0) > 1e-3:
        failures.append(f"scaling_efficiency arithmetic wrong: {eff}")

    def corrupt(mutate, needle):
        bad = copy.deepcopy(good)
        mutate(bad)
        bad_failures = scaling.validate_scaling_report(bad)
        if not any(needle in b for b in bad_failures):
            failures.append(
                f"validator missed a {needle!r} violation: {bad_failures}")

    corrupt(lambda r: r.update(schema="dtf-scaling-0"), "schema")
    corrupt(lambda r: r["cells"][1].pop("provenance"),
            "missing 'provenance'")
    # THE masquerade case: a cell claiming TPU under a CPU header
    corrupt(lambda r: r["cells"][1]["provenance"].update(platform="tpu"),
            "masqueraded")
    corrupt(lambda r: r["cells"][1].update(steps_per_sec=0.0),
            "finite positive")
    corrupt(lambda r: r["cells"][1]["mesh"].update(data=4),
            "does not multiply")
    corrupt(lambda r: r["gates"][0].update(passed=False), "inconsistent")
    corrupt(lambda r: r.update(cells=[]), "no cells")
    return failures


def _check_fleetview() -> list[str]:
    """Fleet-observatory gate (obs/fleetview.py): a worker snapshot
    round-trips through the ``dtf-fleetsnap-1`` validator, a consistent
    set of per-process dumps merges into a valid ``dtf-fleetmerge-1``
    timeline — and the must-fail corpora are each caught: a torn
    snapshot, a snapshot claiming another worker's label, a worker dump
    with no clock anchor, a worker label collision, and causally
    impossible anchors. Pure host code: no device, no jax."""
    import copy
    import os

    from distributed_tensorflow_tpu.obs import fleetview as fv
    from distributed_tensorflow_tpu.obs import flightrec as fr
    from distributed_tensorflow_tpu.obs.registry import Registry

    failures: list[str] = []

    class _Clk:
        def __init__(self, t):
            self.t = float(t)

        def __call__(self):
            return self.t

    with tempfile.TemporaryDirectory(prefix="obs_check_fv_") as d:
        # -- snapshot schema + crash-safety ------------------------------
        wclk = _Clk(100.0)
        wrec = fr.FlightRecorder(clock=wclk)
        wreg = Registry()
        wreg.counter("goodput_productive_seconds_total").inc(3.0)
        exporter = fv.SnapshotExporter(
            fv.fleetsnap_path(d, 0), worker=0, incarnation=1,
            registry=wreg, flightrec=wrec, clock=wclk, min_interval_s=5.0)
        wrec.emit("train_start", step=0)
        path = exporter.export(step=1, phase="train")
        snap = fv.read_snapshot(path)
        for f in fv.validate_snapshot(snap, expect_worker=0):
            failures.append(f"fleetsnap invalid: {f}")
        if exporter.export(step=2) is not None:  # inside the rate limit
            failures.append("exporter ignored min_interval_s")
        if exporter.export(step=2, force=True) is None:
            failures.append("exporter force= did not bypass the rate limit")
        # a crash mid-export leaves a torn .tmp and the PREVIOUS
        # snapshot readable — simulate the torn sibling and verify reads
        # never see it
        # reviewed: deliberately torn scratch sibling for the crash-safety
        # probe — the .tmp path is exactly what a mid-export kill leaves
        with open(path + ".tmp", "w") as f_torn:  # dtflint: disable=atomic-durable-write
            f_torn.write('{"schema": "dtf-fleetsnap-1", "worker"')
        good = fv.read_snapshot(path)
        if good is None or good["seq"] != 2:
            failures.append("previous snapshot unreadable next to a torn "
                            ".tmp")
        # a torn snapshot FILE (external corruption) reads as absent
        torn = os.path.join(d, "torn.json")
        # reviewed: scratch corpus for the must-fail probe
        with open(torn, "w") as f_t:  # dtflint: disable=atomic-durable-write
            f_t.write('{"schema": "dtf-fleetsnap-1", "wor')
        if fv.read_snapshot(torn) is not None:
            failures.append("torn snapshot did not read as absent")
        bad = copy.deepcopy(snap)
        bad["schema"] = "dtf-fleetsnap-0"
        if not any("schema" in f for f in fv.validate_snapshot(bad)):
            failures.append("snapshot validator missed a schema violation")
        if not any("collision" in f
                   for f in fv.validate_snapshot(snap, expect_worker=1)):
            failures.append("snapshot validator missed a worker label "
                            "collision")

        # -- merged timeline + anchor must-fails -------------------------
        pid = os.getpid()
        fclk = _Clk(500.0)
        frec = fr.FlightRecorder(clock=fclk)
        frec.emit("fleet_start", workers=1, incarnation=1)
        fclk.t = 501.0
        frec.emit("fleet_launch", worker=0, incarnation=1, pid=pid)
        fclk.t = 510.0
        frec.emit("fleetsnap_merge", worker=0, seq=1, pid=pid,
                  incarnation=1)
        fclk.t = 540.0
        frec.emit("fleet_done", incarnation=1)
        fleet_dump = frec.dump(os.path.join(d, "fleet.jsonl"), "obs_check")
        wclk.t = 130.0
        wrec.emit("train_stop", step=2, reason="done")
        worker_dump = wrec.dump(os.path.join(d, "w0.jsonl"), "obs_check",
                                extra={"worker": 0, "incarnation": 1})
        header, events, merge_failures = fv.merge_timelines(
            fleet_dump, [worker_dump], reason="obs_check")
        for f in merge_failures:
            failures.append(f"consistent dumps failed to merge: {f}")
        merged = os.path.join(d, "merged.jsonl")
        fv.write_merged(merged, header, events)
        for f in fv.validate_merged_dump(merged):
            failures.append(f"merged dump invalid: {f}")
        if not fr.contains_in_order(events, [
                ("fleet_launch", {}), ("train_start", {"src": "w0i1"}),
                ("fleetsnap_merge", {}), ("fleet_done", {})]):
            failures.append("merged timeline lost the launch->merge->done "
                            "causal order")
        # no anchor: a fleet dump with no fleet_launch for this worker
        bare = fr.FlightRecorder(clock=_Clk(500.0))
        bare.emit("fleet_start", workers=1, incarnation=1)
        bare_dump = bare.dump(os.path.join(d, "bare.jsonl"), "obs_check")
        _, _, mf = fv.merge_timelines(bare_dump, [worker_dump])
        if not any("anchor missing" in f for f in mf):
            failures.append(f"merge missed a missing clock anchor: {mf}")
        # collision: two dumps claiming the same (worker, incarnation)
        _, _, mf = fv.merge_timelines(fleet_dump,
                                      [worker_dump, worker_dump])
        if not any("collision" in f for f in mf):
            failures.append(f"merge missed a worker label collision: {mf}")
        # impossible anchors: the worker's life (30s) cannot fit the
        # fleet's launch->done window (1s)
        tight = fr.FlightRecorder(clock=_Clk(500.0))
        tight.emit("fleet_launch", worker=0, incarnation=1, pid=pid)
        tight_clk = _Clk(501.0)
        tight.clock = tight_clk
        tight.emit("fleet_done", incarnation=1)
        tight_dump = tight.dump(os.path.join(d, "tight.jsonl"), "obs_check")
        _, _, mf = fv.merge_timelines(tight_dump, [worker_dump])
        if not any("inconsistent" in f for f in mf):
            failures.append(f"merge missed inconsistent clock anchors: {mf}")
        # missing identity: a dump without worker/incarnation can't merge
        anon_dump = wrec.dump(os.path.join(d, "anon.jsonl"), "obs_check")
        _, _, mf = fv.merge_timelines(fleet_dump, [anon_dump])
        if not any("identity" in f for f in mf):
            failures.append(f"merge missed a missing worker identity: {mf}")
    return failures


def _check_reqtrace() -> list[str]:
    """Request-ledger gate (obs/reqtrace.py): a two-process fake-clock
    serve story — router + one replica with a skewed clock, a
    death-requeue hop included — must dump valid ``dtf-reqtrace-1``
    files, merge into ONE per-request timeline whose spans still
    partition wall time, and the must-fail corpora — a torn dump, a
    span ending before it starts, an unknown phase, a duplicate rid —
    must each be caught. Pure host code: no device, no jax."""
    import os

    from distributed_tensorflow_tpu.obs import reqtrace as rt

    failures: list[str] = []

    class _Clk:
        def __init__(self, t):
            self.t = float(t)

        def __call__(self):
            return self.t

    with tempfile.TemporaryDirectory(prefix="obs_check_rt_") as d:
        rclk, wclk = _Clk(100.0), _Clk(900.0)  # 800s apart, same story
        router = rt.ReqTrace(src="router", clock=rclk)
        replica = rt.ReqTrace(src="w0i0", clock=wclk)

        # rid 1: submit -> route -> ingest -> admit/prefill -> token ->
        # death-requeue -> re-route (the chain the serve seams emit)
        router.transition(1, "queue_wait", lane="interactive")
        rclk.t = 101.5
        router.transition(1, "route", replica=0, requeue=0)
        wclk.t = 901.5  # ingest at the same fake instant as dispatch:
        # the dispatch->ingest lower bound recovers the skew EXACTLY
        replica.transition(1, "admission_block", requeue=0)
        wclk.t = 902.0
        replica.transition(1, "prefill_chunks", slot=0)
        wclk.t = 903.0
        replica.transition(1, "decode_gap")  # replica samples...
        rclk.t = 103.0
        router.transition(1, "decode_gap", n=1)  # ...router delivers
        rclk.t = 104.0
        router.transition(1, "requeue_reprefill", replica=0, delivered=1)
        rclk.t = 105.0
        router.finish(1, "max_new_tokens")
        try:
            router.transition(1, "warp_speed")  # dtflint: disable=closed-vocab
            failures.append("transition accepted an unknown phase")
        except ValueError:
            pass

        rp = router.dump(os.path.join(d, "router.jsonl"), "obs_check")
        wp = replica.dump(os.path.join(d, "w0.jsonl"), "obs_check",
                          extra={"worker": 0, "incarnation": 0})
        for p in (rp, wp):
            for f in rt.validate_dump(p):
                failures.append(f"reqtrace dump invalid: {f}")

        header, merged, mf = rt.merge_traces(rp, [wp], reason="obs_check")
        failures.extend(f"consistent traces failed to merge: {m}"
                        for m in mf)
        off = header.get("offsets", {}).get("w0i0")
        if off is None or abs(off - (-800.0)) > 1e-6:
            failures.append(f"merge recovered offset {off}, want -800.0")
        if len(merged) != 1 or merged[0]["rid"] != 1:
            failures.append(f"merged records wrong: {merged}")
        else:
            rec = merged[0]
            if sorted(rec["sources"]) != ["router", "w0i0"]:
                failures.append(f"merged sources wrong: {rec['sources']}")
            try:
                parts = rt.phase_partition(rec)
                if abs(parts[0][1] - 100.0) > 1e-9 \
                        or abs(parts[-1][2] - 105.0) > 1e-9:
                    failures.append(
                        f"merged timeline bounds wrong: {parts}")
            except ValueError as e:
                failures.append(f"merged spans do not partition: {e}")
            if not rt.span_chain_matches(rec, [
                    "queue_wait", "route", "admission_block",
                    "prefill_chunks", "decode_gap", "requeue_reprefill",
                    ("finish", {"reason": "max_new_tokens"})]):
                failures.append("merged record lost the causal chain")
        mp = os.path.join(d, "merged.jsonl")
        rt.write_merged(mp, header, merged)
        if rt.load_dump(mp)[0].get("schema") != rt.MERGED_SCHEMA:
            failures.append("write_merged lost the merged schema tag")

        # the validator must catch what transition() can never produce
        with open(rp) as f_in:
            lines = f_in.read().splitlines()
        ok_rec = json.loads(lines[1])

        def corrupt(name, mutate_lines, needle):
            bad = os.path.join(d, name)
            # reviewed: scratch corpus for the validator's must-fail
            # probes, torn-on-crash is irrelevant (tempdir-only file)
            with open(bad, "w") as f_out:  # dtflint: disable=atomic-durable-write
                f_out.write("\n".join(mutate_lines) + "\n")
            got = rt.validate_dump(bad)
            if not any(needle in g for g in got):
                failures.append(
                    f"validator missed a {needle!r} violation: {got}")

        # torn dump: header claims more records than the file holds
        corrupt("torn.jsonl", [lines[0]], "torn dump")
        # span end before start
        bent = json.loads(lines[1])
        bent["spans"][0]["t1"] = bent["spans"][0]["t0"] - 1.0
        corrupt("bent.jsonl", [lines[0], json.dumps(bent)], "before start")
        # unknown phase
        alien = json.loads(lines[1])
        alien["spans"][0]["phase"] = "warp_speed"
        corrupt("alien.jsonl", [lines[0], json.dumps(alien)],
                "unknown phase")
        # duplicate rid within one dump
        two = json.loads(lines[0])
        two["records"] = 2
        corrupt("dup.jsonl",
                [json.dumps(two), json.dumps(ok_rec), json.dumps(ok_rec)],
                "duplicate rid")
    return failures


def main() -> int:
    return 1 if check() else 0


if __name__ == "__main__":
    raise SystemExit(main())
