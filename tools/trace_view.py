#!/usr/bin/env python
"""Render request-ledger dumps as per-request waterfalls and attribute
tail latency to named lifecycle phases.

Inputs are ``dtf-reqtrace-1`` dumps (obs/reqtrace.py): one from the
router process (header ``src == "router"``) plus any number of replica
dumps (``src == w<i>i<k>``). The tool validates every dump, aligns the
replica clocks onto the router clock with the per-request anchor
protocol (dispatch happens-before ingest / sample happens-before
delivery — ``obs.reqtrace.merge_traces``), and rebuilds each request as
ONE gap-free span timeline, even when a death-requeue hopped it across
replica processes. A single input whose header carries
``dtf-reqtrace-merged-1`` is rendered as an already-merged trace.

Outputs:

- a per-rid summary (and with ``--rid`` a full text waterfall);
- ``--out merged.jsonl`` — the merged trace, atomically written;
- ``--chrome trace.json`` — Chrome-trace JSON (load in
  ``chrome://tracing`` / Perfetto; one track per rid);
- ``--slowest K`` — the tail-attribution report: for the K slowest
  requests by TTFT, decompose TTFT into per-phase seconds
  (queue_wait / route / admission_block / prefill_chunks /
  requeue_reprefill / ...). Because spans partition wall time, the
  phase durations must sum to the measured TTFT within 1% — the tool
  FAILS if they do not (a torn or mis-merged trace cannot silently
  produce a plausible report);
- ``--expect p1,p2[attr=v],...`` — causal gate (exit 1 on miss): some
  request's merged lifecycle must contain the phases as a subsequence
  (``finish[reason=...]`` matches the terminal record). With ``--rid``
  the gate pins that specific request. ``--require-replicas N``
  additionally requires the matching request to carry spans from at
  least N distinct replica processes — the killed-request gate in
  tools/ci_fast.sh proves the merged trace really spans both lives.

Usage:
    python tools/trace_view.py router.jsonl replica*.jsonl \
        --out merged.jsonl --slowest 3 \
        --expect 'queue_wait,route,admission_block,prefill_chunks' \
        --require-replicas 2
"""

import argparse
import json
import os
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

#: relative slack on "phase durations sum to measured latency" — the
#: acceptance bar; a correct merge is exact up to float rounding
SUM_TOLERANCE = 0.01


def parse_expect(spec: str):
    """``phase`` or ``phase[attr=v,...]`` items, comma-separated at the
    top level (tools/postmortem.py's expect grammar, phases for kinds)."""
    from tools.postmortem import parse_expect as pm_parse

    return pm_parse(spec)


def _sources(rec) -> set:
    srcs = set(rec.get("sources") or ())
    for span in rec.get("spans", ()):
        if "src" in span:
            srcs.add(span["src"])
    return srcs


def _replica_sources(rec) -> set:
    return {s for s in _sources(rec) if s != "router"}


def _span_attrs(span) -> dict:
    return {k: v for k, v in span.items()
            if k not in ("phase", "t0", "t1", "src")}


def render_waterfall(rec, out=sys.stdout) -> None:
    """Text waterfall for one request, t=0 at its first transition."""
    from distributed_tensorflow_tpu.obs import reqtrace as rt

    spans = rec.get("spans", ())
    if not spans:
        print(f"rid {rec.get('rid')}: no spans", file=out)
        return
    t_base = float(spans[0]["t0"])
    t_end = max(float(s.get("t1") or s["t0"]) for s in spans)
    total = max(t_end - t_base, 1e-12)
    print(f"rid {rec['rid']}  finish={rec.get('finish_reason')}  "
          f"sources={','.join(sorted(_sources(rec))) or '-'}  "
          f"total={total:.6f}s", file=out)
    for span in spans:
        t0 = float(span["t0"]) - t_base
        t1 = (float(span["t1"]) - t_base
              if span.get("t1") is not None else t0)
        # proportional bar: where in the request's life this span sits
        width = 32
        a = int(round(t0 / total * width))
        b = max(a + 1, int(round(t1 / total * width)))
        bar = " " * a + "#" * (b - a)
        attrs = " ".join(f"{k}={v}" for k, v in
                         sorted(_span_attrs(span).items()))
        src = f"{span.get('src', ''):<8}"
        print(f"  t+{t0:9.6f}  {t1 - t0:9.6f}s  |{bar:<{width}}| "
              f"{src}{span['phase']:<18} {attrs}".rstrip(), file=out)
    ttft = rt.first_token_t(rec)
    if ttft is not None:
        print(f"  ttft={ttft - t_base:.6f}s", file=out)


def chrome_trace(records) -> list:
    """Chrome-trace "X" (complete) events, one track per rid, µs since
    the earliest transition across all records."""
    t_base = min((float(s["t0"]) for r in records
                  for s in r.get("spans", ())), default=0.0)
    events = []
    for rec in records:
        for span in rec.get("spans", ()):
            t0 = float(span["t0"])
            t1 = float(span["t1"]) if span.get("t1") is not None else t0
            args = _span_attrs(span)
            if span.get("src"):
                args["src"] = span["src"]
            events.append({
                "name": span["phase"], "cat": "reqtrace", "ph": "X",
                "ts": (t0 - t_base) * 1e6, "dur": (t1 - t0) * 1e6,
                "pid": 1, "tid": int(rec["rid"]), "args": args,
            })
    return events


def tail_report(records, k, out=sys.stdout) -> list:
    """The tail-attribution report: slowest-k by TTFT, each TTFT
    decomposed into per-phase seconds. Returns failures (a decomposition
    that does not sum to the measured TTFT within ``SUM_TOLERANCE``)."""
    from distributed_tensorflow_tpu.obs import reqtrace as rt

    failures = []
    rows = []
    for rec in records:
        spans = rec.get("spans", ())
        if not spans:
            continue
        t_submit = float(spans[0]["t0"])
        t_first = rt.first_token_t(rec)
        if t_first is None:
            continue  # never delivered a token: no TTFT to attribute
        try:
            parts = rt.attribute_window(rec, t_submit, t_first)
        except ValueError as e:
            failures.append(f"rid {rec['rid']}: {e}")
            continue
        rows.append((t_first - t_submit, rec, parts))
    rows.sort(key=lambda r: -r[0])
    print(f"slowest {min(k, len(rows))} of {len(rows)} requests by TTFT:",
          file=out)
    for ttft, rec, parts in rows[:k]:
        total = sum(parts.values())
        if abs(total - ttft) > max(SUM_TOLERANCE * ttft, 1e-9):
            failures.append(
                f"rid {rec['rid']}: phase durations sum to {total:.6f}s "
                f"but measured TTFT is {ttft:.6f}s (>1% apart — torn or "
                f"mis-merged trace)")
        breakdown = " ".join(
            f"{phase}={parts[phase]:.6f}"
            for phase in sorted(parts, key=parts.get, reverse=True))
        print(f"  rid {rec['rid']:<5} ttft={ttft:.6f}s  "
              f"[{','.join(sorted(_replica_sources(rec))) or '-'}]  "
              f"{breakdown}", file=out)
    return failures


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("dumps", nargs="+",
                    help="dtf-reqtrace-1 dumps (one with src=router) or "
                         "a single dtf-reqtrace-merged-1 file")
    ap.add_argument("--out", help="write the merged trace here (atomic)")
    ap.add_argument("--chrome", help="write Chrome-trace JSON here")
    ap.add_argument("--rid", type=int, default=None,
                    help="waterfall (and pin --expect to) this request")
    ap.add_argument("--slowest", type=int, default=0, metavar="K",
                    help="tail-attribution report for the K slowest "
                         "requests by TTFT")
    ap.add_argument("--expect", action="append", default=[],
                    help="phase chain gate: p1,p2[attr=v],... "
                         "(repeatable; finish[reason=..] is terminal)")
    ap.add_argument("--require-replicas", type=int, default=0, metavar="N",
                    help="the gated request must carry spans from >= N "
                         "distinct replica processes")
    args = ap.parse_args(argv)

    from distributed_tensorflow_tpu.obs import reqtrace as rt

    failures = []
    first_header = {}
    try:
        first_header, _ = rt.load_dump(args.dumps[0])
    except (OSError, ValueError) as e:
        print(f"FAIL: {args.dumps[0]}: {e}", file=sys.stderr)
        return 1

    if len(args.dumps) == 1 \
            and first_header.get("schema") == rt.MERGED_SCHEMA:
        header, records = rt.load_dump(args.dumps[0])
    else:
        routers = []
        for path in args.dumps:
            for f in rt.validate_dump(path):
                failures.append(f"{path}: {f}")
            try:
                h, _ = rt.load_dump(path)
            except (OSError, ValueError):
                continue
            if h.get("src") == "router":
                routers.append(path)
        if len(routers) != 1:
            failures.append(
                f"need exactly one dump with src=router, got {routers}")
        if failures:
            for f in failures:
                print(f"FAIL: {f}", file=sys.stderr)
            return 1
        replicas = [p for p in args.dumps if p != routers[0]]
        header, records, merge_failures = rt.merge_traces(
            routers[0], replicas, reason="trace_view")
        failures.extend(merge_failures)

    if args.out and not failures:
        rt.write_merged(args.out, header, records)
        print(f"merged trace -> {args.out} "
              f"({len(records)} requests, offsets "
              f"{header.get('offsets', {})})")
    if args.chrome and not failures:
        tmp = f"{args.chrome}.tmp"
        with open(tmp, "w") as f:
            json.dump({"traceEvents": chrome_trace(records),
                       "displayTimeUnit": "ms"}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, args.chrome)
        print(f"chrome trace -> {args.chrome}")

    by_rid = {rec["rid"]: rec for rec in records}
    if args.rid is not None:
        rec = by_rid.get(args.rid)
        if rec is None:
            failures.append(f"rid {args.rid} not in the merged trace")
        else:
            render_waterfall(rec)
    else:
        for rec in records:
            spans = rec.get("spans", ())
            dur = (max((float(s.get("t1") or s["t0"])) for s in spans)
                   - float(spans[0]["t0"])) if spans else 0.0
            print(f"rid {rec['rid']:<5} spans={len(spans):<4} "
                  f"finish={rec.get('finish_reason')}  "
                  f"sources={','.join(sorted(_sources(rec))) or '-'}  "
                  f"total={dur:.6f}s")

    if args.slowest:
        failures.extend(tail_report(records, args.slowest))

    gated = ([by_rid[args.rid]]
             if args.rid is not None and args.rid in by_rid
             else records)
    for spec in args.expect:
        chain = parse_expect(spec)
        hits = [rec for rec in gated if rt.span_chain_matches(rec, chain)]
        if args.require_replicas:
            hits = [rec for rec in hits
                    if len(_replica_sources(rec)) >= args.require_replicas]
        if not hits:
            failures.append(
                f"no request matches expect chain {spec!r}"
                + (f" with >= {args.require_replicas} replica sources"
                   if args.require_replicas else ""))
        else:
            print(f"expect ok: {spec!r} matched rid(s) "
                  f"{sorted(r['rid'] for r in hits)}")
    if not args.expect and args.require_replicas:
        hits = [rec for rec in gated
                if len(_replica_sources(rec)) >= args.require_replicas]
        if not hits:
            failures.append(
                f"no request carries spans from >= "
                f"{args.require_replicas} replica processes")

    for f in failures:
        print(f"FAIL: {f}", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
