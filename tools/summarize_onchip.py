#!/usr/bin/env python
"""Summarize an on-chip session's logs into a PERF_NOTES-ready digest.

Reads every *.log in the given directory (default /tmp/onchip_r3b),
pulls the JSON metric rows and key validator/microbench lines, and
prints a markdown digest: one table row per bench metric plus notable
pass/fail lines. Wall-clock matters when a relay window is open — this
turns 'analyze and commit the evidence' into one command.

Usage: python tools/summarize_onchip.py [logdir]
"""

from __future__ import annotations

import glob
import json
import os
import sys


def main() -> None:
    logdir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/onchip_r3b"
    logs = sorted(glob.glob(os.path.join(logdir, "*.log")))
    if not logs:
        raise SystemExit(f"no logs under {logdir}")

    rows, notes = [], []
    for path in logs:
        name = os.path.basename(path)[:-4]
        with open(path, errors="replace") as f:
            text = f.read()
        for line in text.splitlines():
            line = line.strip()
            if line.startswith("{") and '"metric"' in line:
                try:
                    rows.append((name, json.loads(line)))
                except json.JSONDecodeError:
                    pass
            elif line.startswith(("ALL OK", "FAILURES", "FAIL ")):
                notes.append((name, line[:120]))
            elif "block-impl A/B:" in line:
                notes.append((name, line.split("] ")[-1][:120]))

    print(f"## On-chip digest: {logdir} ({len(logs)} logs)\n")
    if rows:
        print("| step | metric | value | unit | extras |")
        print("|---|---|---|---|---|")
        for name, r in rows:
            extras = {k: v for k, v in r.items()
                      if k not in ("metric", "value", "unit")
                      and not isinstance(v, (dict, list))}
            extra_s = " ".join(
                f"{k}={v}" for k, v in sorted(extras.items())
                if k in ("mfu", "platform", "block_impl", "raw_gbps",
                         "raw_tflops", "pct_of_v5e_spec",
                         "pipeline_efficiency", "fed_data",
                         "alt_block_impl", "alt_images_per_sec_per_chip",
                         "attention_impl", "fused_ln_matmul", "seq_len",
                         "model", "dispatch_fetch_overhead_ms"))
            print(f"| {name} | {r['metric']} | {r['value']} "
                  f"| {r.get('unit', '')} | {extra_s} |")
    if notes:
        print("\nNotable lines:")
        for name, line in notes:
            print(f"- `{name}`: {line}")


if __name__ == "__main__":
    main()
