#!/usr/bin/env python
"""Compiled-mode (Mosaic) validation of the fused conv+BN kernels on the
real chip: small-shape forward + gradient parity vs the jnp oracle for
every static config the ResNet integration uses, then one fused
bottleneck block vs the standard flax block. Fast (<2 min warm) and
read-only — run this before any fused bench.

Exit code 0 = every check passed.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

# Honor an explicit JAX_PLATFORMS even though the site plugin pre-set the
# config at import (bench.py / parallel/cluster.py note).
_env_platforms = os.environ.get("JAX_PLATFORMS")
if _env_platforms and jax.config.jax_platforms != _env_platforms:
    jax.config.update("jax_platforms", _env_platforms)

import jax.flatten_util
import jax.numpy as jnp
import numpy as np

from distributed_tensorflow_tpu.ops.fused_conv_bn import (
    bn_scale_shift, conv1x1_bn_act, conv1x1_bn_act_reference,
    moments_from_sums,
)


def check(name, got, want, tol):
    g, w = np.asarray(got, np.float32), np.asarray(want, np.float32)
    err = float(np.max(np.abs(g - w) / (np.abs(w) + 1.0)))
    ok = err <= tol
    print(f"{'ok ' if ok else 'FAIL'} {name}: rel_err={err:.2e} (tol {tol})")
    return ok


def main():
    print("devices:", jax.devices(), flush=True)
    r = np.random.RandomState(0)
    M, cin, cout = 512, 64, 128
    x = jnp.asarray(r.randn(M, cin), jnp.bfloat16)
    w = jnp.asarray(r.randn(cin, cout) * 0.1, jnp.bfloat16)
    gamma = jnp.asarray(r.rand(cin) + 0.5, jnp.float32)
    beta = jnp.asarray(r.randn(cin) * 0.1, jnp.float32)
    mean = jnp.asarray(r.randn(cin) * 0.2, jnp.float32)
    var = jnp.asarray(r.rand(cin) + 0.3, jnp.float32)
    scale, shift = bn_scale_shift(mean, var, gamma, beta, 1e-5)
    ok = True

    for prologue in (False, True):
        args = (x, w, scale, shift) if prologue else (x, w)
        got = jax.jit(
            lambda *a: conv1x1_bn_act(*a, relu=True, emit_stats=True)
        )(*args)
        want = conv1x1_bn_act_reference(*args, relu=True, emit_stats=True)
        for nm, g, wn in zip(("y", "sum", "ssq"), got, want):
            ok &= check(f"fwd prologue={prologue} {nm}", g, wn, 3e-2)

        def loss(fn):
            def go(x, w, scale, shift):
                a = (x, w, scale, shift) if prologue else (x, w)
                y, s, q = fn(*a, relu=True, emit_stats=True)
                mu, v = moments_from_sums(s, q, y.shape[0])
                return ((y.astype(jnp.float32) ** 2).mean()
                        + (mu * mu).sum() + jnp.sqrt(v + 1e-3).sum())
            return go

        got_g = jax.jit(jax.grad(loss(conv1x1_bn_act), argnums=(0, 1, 2, 3))
                        )(x, w, scale, shift)
        want_g = jax.grad(loss(conv1x1_bn_act_reference),
                          argnums=(0, 1, 2, 3))(x, w, scale, shift)
        n = 4 if prologue else 2
        for nm, g, wn in list(zip(("dx", "dw", "dscale", "dshift"),
                                  got_g, want_g))[:n]:
            ok &= check(f"grad prologue={prologue} {nm}", g, wn, 5e-2)

    # one fused bottleneck vs the standard flax block, train fwd + grad
    from distributed_tensorflow_tpu.models import common
    from distributed_tensorflow_tpu.models.resnet import ResNet50, ResNetConfig

    kw = dict(stage_sizes=(1,), width=16, num_classes=10, dtype="bfloat16")
    m_std = ResNet50(ResNetConfig(**kw))
    m_f = ResNet50(ResNetConfig(block_impl="fused", **kw))
    params, mstate = common.make_init_fn(m_std, (32, 32, 3))(
        jax.random.PRNGKey(0)
    )
    xb = jnp.asarray(r.randn(8, 32, 32, 3), jnp.float32)

    def loss_model(m):
        def go(p):
            out, _ = m.apply({"params": p, **mstate}, xb, train=True,
                             mutable=["batch_stats"])
            return (out.astype(jnp.float32) ** 2).mean()
        return go

    ok &= check("block fwd", jax.jit(loss_model(m_f))(params),
                jax.jit(loss_model(m_std))(params), 3e-2)
    gf = jax.jit(jax.grad(loss_model(m_f)))(params)
    gs = jax.jit(jax.grad(loss_model(m_std)))(params)
    ff, _ = jax.flatten_util.ravel_pytree(jax.device_get(gf))
    fs, _ = jax.flatten_util.ravel_pytree(jax.device_get(gs))
    ok &= check("block grad", ff, fs, 5e-2)

    print("ALL OK" if ok else "FAILURES", flush=True)
    raise SystemExit(0 if ok else 1)


if __name__ == "__main__":
    main()
