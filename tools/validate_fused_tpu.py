#!/usr/bin/env python
"""Compiled-mode (Mosaic) validation of every fused Pallas kernel on the
real chip: small-shape forward + gradient parity vs the jnp oracles for
(a) the conv1x1+BN kernels at every static config the ResNet integration
uses, (b) the LayerNorm+matmul kernel, then whole-model comparisons —
a fused-LN pre-LN transformer and a fused bottleneck ResNet vs their
standard flax twins (fwd + full grad pytree). Fast (<3 min warm) and
read-only — run this before any fused bench.

Gradient/model checks use a max-normalized error (err relative to the
largest entry of the oracle tensor) so tiny-magnitude gradients cannot
pass vacuously under the elementwise damped metric.

Exit code 0 = every check passed.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

# Honor an explicit JAX_PLATFORMS even though the site plugin pre-set the
# config at import (bench.py / parallel/cluster.py note).
_env_platforms = os.environ.get("JAX_PLATFORMS")
if _env_platforms and jax.config.jax_platforms != _env_platforms:
    jax.config.update("jax_platforms", _env_platforms)

import jax.numpy as jnp
import numpy as np

from distributed_tensorflow_tpu.ops.fused_conv_bn import (
    bn_scale_shift, conv1x1_bn_act, conv1x1_bn_act_reference,
    moments_from_sums,
)


def check(name, got, want, tol):
    g, w = np.asarray(got, np.float32), np.asarray(want, np.float32)
    err = float(np.max(np.abs(g - w) / (np.abs(w) + 1.0)))
    ok = err <= tol
    print(f"{'ok ' if ok else 'FAIL'} {name}: rel_err={err:.2e} (tol {tol})")
    return ok


def check_scaled(name, got, want, tol):
    """Max-abs error relative to the oracle's own largest entry.

    Unlike ``check`` this cannot be satisfied vacuously by a
    small-magnitude tensor: an all-zero ``got`` scores err = 1.0.
    """
    g, w = np.asarray(got, np.float32), np.asarray(want, np.float32)
    scale = float(np.max(np.abs(w)))
    if scale == 0.0:  # not assert: must survive python -O
        print(f"FAIL {name}: oracle is all-zero, check would be vacuous")
        return False
    err = float(np.max(np.abs(g - w))) / scale
    ok = err <= tol
    print(f"{'ok ' if ok else 'FAIL'} {name}: scaled_err={err:.2e} (tol {tol})")
    return ok


def bench_shape_sweep(r) -> bool:
    """Compile/execute every fused-kernel shape the batch-256 ResNet-50
    and bench BERT/GPT paths actually emit (TPU only).

    Round-3 on-chip lesson: the dw kernel's VMEM footprint is
    shape-dependent, and small-shape parity passed while the REAL bench
    shape [12544, 512]x[12544, 2048] blew the 16 MB scoped limit at
    compile time — this sweep is what makes the validator a gate for the
    bench. Every check is exception-guarded: one bad shape must record
    FAIL and keep sweeping, not abort a scarce chip window.

    VALIDATE_PALLAS_BWD selects what runs:
      "0" (default) — default-path (xla-backward) fwd+grad execute only;
      "1"           — both the default path and the Pallas-backward
                      AOT compiles;
      "only"        — Pallas-backward AOT compiles alone (the late,
                      may-stall step of onchip_round3b.sh; r3a saw a
                      >10 min stall in this path at the s3_conv1 shape,
                      microbench_grad rc=124).
    """
    from distributed_tensorflow_tpu.ops.fused_ln_matmul import ln_matmul

    mode = os.environ.get("VALIDATE_PALLAS_BWD", "0")
    run_default = mode in ("0", "1")
    run_pallas = mode in ("1", "only")
    if run_pallas:
        # the sweep MEASURES the known-slow shapes (it is how entries in
        # _tiling.PALLAS_BWD_KNOWN_SLOW get confirmed or retired), so it
        # bypasses the landmine guard and times every compile
        os.environ["DTF_FUSED_BWD_FORCE"] = "1"
    if jax.default_backend() != "tpu":
        print("skip bench-shape sweep (not on TPU; interpret mode would "
              "not exercise Mosaic VMEM limits)")
        return True
    ok = True

    def guarded(tag, fn):
        nonlocal ok
        try:
            fn()
            return True
        except Exception as e:  # noqa: BLE001 — report, don't abort
            print(f"FAIL {tag}: {type(e).__name__}: {str(e)[:200]}")
            ok = False
            return False

    conv_shapes = [  # batch-256 ResNet-50 1x1 convs, all stages
        (200704, 64, 256), (200704, 256, 64), (200704, 256, 128),
        (50176, 128, 512), (50176, 512, 128), (50176, 512, 256),
        (12544, 256, 1024), (12544, 1024, 256), (12544, 1024, 512),
        (3136, 512, 2048), (3136, 2048, 512),
        (12544, 512, 2048), (12544, 2048, 512),  # the r3 OOM shapes
    ]
    for (bM, bci, bco) in conv_shapes:
        bx = jnp.asarray(r.randn(bM, bci) * 0.1, jnp.bfloat16)
        bw = jnp.asarray(r.randn(bci, bco) * 0.05, jnp.bfloat16)
        bs = jnp.asarray(r.rand(bci) + 0.5, jnp.float32)
        bsh = jnp.asarray(r.randn(bci) * 0.1, jnp.float32)

        def conv_loss(impl):
            def go(x, w, s, sh):
                y, cs, cq = conv1x1_bn_act(x, w, s, sh, relu=True,
                                           emit_stats=True, bwd_impl=impl)
                return ((y.astype(jnp.float32) ** 2).mean()
                        + cs.sum() * 1e-6 + cq.sum() * 1e-9)
            return go

        if run_default:
            def execute():
                val, grads = jax.jit(jax.value_and_grad(
                    conv_loss("xla"), argnums=(0, 1, 2, 3)))(
                        bx, bw, bs, bsh)
                fin = all(bool(jnp.all(jnp.isfinite(
                    g.astype(jnp.float32)))) for g in grads)
                if not (np.isfinite(float(val)) and fin):
                    raise RuntimeError(
                        f"loss={float(val)} grads_finite={fin}")
                print(f"ok  bench-shape conv1x1 M={bM} {bci}->{bco}: "
                      f"loss={float(val):.3e}")

            guarded(f"bench-shape conv1x1 M={bM} {bci}->{bco}", execute)

        if run_pallas:
            def compile_pallas():
                import time as _t

                t0 = _t.perf_counter()
                jax.jit(jax.value_and_grad(
                    conv_loss("pallas"), argnums=(0, 1, 2, 3))).lower(
                        bx, bw, bs, bsh).compile()
                print(f"ok  bench-shape conv1x1 pallas-bwd compile "
                      f"M={bM} {bci}->{bco} ({_t.perf_counter()-t0:.1f}s)")

            guarded(f"bench-shape conv1x1 pallas-bwd compile M={bM} "
                    f"{bci}->{bco}", compile_pallas)

    ln_shapes = [  # bench_bert/gpt ln_matmul edges at bench batch
        (16384, 768, 2304), (16384, 768, 3072), (16384, 3072, 768),
        (32768, 1024, 4096),  # gpt long-context edge
    ]
    for (bM, bd, bn_) in ln_shapes:
        bx = jnp.asarray(r.randn(bM, bd) * 0.1, jnp.bfloat16)
        bg = jnp.asarray(r.rand(bd) + 0.5, jnp.float32)
        bb = jnp.asarray(r.randn(bd) * 0.1, jnp.float32)
        bw = jnp.asarray(r.randn(bd, bn_) * 0.02, jnp.bfloat16)
        bbias = jnp.asarray(r.randn(bn_) * 0.1, jnp.float32)

        def ln_loss_of(impl):
            def go(x, g, b, w, bias):
                y = ln_matmul(x, g, b, w, bias, bwd_impl=impl)
                return (y.astype(jnp.float32) ** 2).mean()
            return go

        if run_default:
            def execute_ln():
                val, grads = jax.jit(jax.value_and_grad(
                    ln_loss_of("xla"), argnums=(0, 1, 2, 3, 4)))(
                        bx, bg, bb, bw, bbias)
                fin = all(bool(jnp.all(jnp.isfinite(
                    g.astype(jnp.float32)))) for g in grads)
                if not (np.isfinite(float(val)) and fin):
                    raise RuntimeError(
                        f"loss={float(val)} grads_finite={fin}")
                print(f"ok  bench-shape ln_matmul M={bM} {bd}->{bn_}: "
                      f"loss={float(val):.3e}")

            guarded(f"bench-shape ln_matmul M={bM} {bd}->{bn_}",
                    execute_ln)

        if run_pallas:
            def compile_ln_pallas():
                import time as _t

                t0 = _t.perf_counter()
                jax.jit(jax.value_and_grad(
                    ln_loss_of("pallas"), argnums=(0, 1, 2, 3, 4))).lower(
                        bx, bg, bb, bw, bbias).compile()
                print(f"ok  bench-shape ln_matmul pallas-bwd compile "
                      f"M={bM} {bd}->{bn_} ({_t.perf_counter()-t0:.1f}s)")

            guarded(f"bench-shape ln_matmul pallas-bwd compile M={bM} "
                    f"{bd}->{bn_}", compile_ln_pallas)

    return ok


def main():
    print("devices:", jax.devices(), flush=True)
    r = np.random.RandomState(0)
    if os.environ.get("VALIDATE_PALLAS_BWD") == "only":
        # the may-stall late step of a chip session: just the gated
        # Pallas-backward compiles, no duplicate parity/default sweep
        ok = bench_shape_sweep(r)
        print("ALL OK" if ok else "FAILURES", flush=True)
        raise SystemExit(0 if ok else 1)
    M, cin, cout = 512, 64, 128
    x = jnp.asarray(r.randn(M, cin), jnp.bfloat16)
    w = jnp.asarray(r.randn(cin, cout) * 0.1, jnp.bfloat16)
    gamma = jnp.asarray(r.rand(cin) + 0.5, jnp.float32)
    beta = jnp.asarray(r.randn(cin) * 0.1, jnp.float32)
    mean = jnp.asarray(r.randn(cin) * 0.2, jnp.float32)
    var = jnp.asarray(r.rand(cin) + 0.3, jnp.float32)
    scale, shift = bn_scale_shift(mean, var, gamma, beta, 1e-5)
    ok = True

    for prologue in (False, True):
        args = (x, w, scale, shift) if prologue else (x, w)
        got = jax.jit(
            lambda *a: conv1x1_bn_act(*a, relu=True, emit_stats=True)
        )(*args)
        want = conv1x1_bn_act_reference(*args, relu=True, emit_stats=True)
        for nm, g, wn in zip(("y", "sum", "ssq"), got, want):
            ok &= check(f"fwd prologue={prologue} {nm}", g, wn, 3e-2)

        def loss(fn):
            def go(x, w, scale, shift):
                a = (x, w, scale, shift) if prologue else (x, w)
                y, s, q = fn(*a, relu=True, emit_stats=True)
                mu, v = moments_from_sums(s, q, y.shape[0])
                return ((y.astype(jnp.float32) ** 2).mean()
                        + (mu * mu).sum() + jnp.sqrt(v + 1e-3).sum())
            return go

        got_g = jax.jit(jax.grad(loss(conv1x1_bn_act), argnums=(0, 1, 2, 3))
                        )(x, w, scale, shift)
        want_g = jax.grad(loss(conv1x1_bn_act_reference),
                          argnums=(0, 1, 2, 3))(x, w, scale, shift)
        n = 4 if prologue else 2
        for nm, g, wn in list(zip(("dx", "dw", "dscale", "dshift"),
                                  got_g, want_g))[:n]:
            ok &= check(f"grad prologue={prologue} {nm}", g, wn, 5e-2)

    # ---- fused LayerNorm+matmul (ops/fused_ln_matmul.py) ----------------
    from distributed_tensorflow_tpu.ops.fused_ln_matmul import (
        ln_matmul, ln_matmul_reference,
    )

    M2, d, nn = 1024, 768, 768
    lx = jnp.asarray(r.randn(M2, d), jnp.bfloat16)
    lg = jnp.asarray(r.rand(d) + 0.5, jnp.float32)
    lb = jnp.asarray(r.randn(d) * 0.1, jnp.float32)
    lw = jnp.asarray(r.randn(d, nn) * 0.02, jnp.bfloat16)
    lbias = jnp.asarray(r.randn(nn) * 0.1, jnp.float32)

    got = jax.jit(ln_matmul)(lx, lg, lb, lw, lbias)
    want = ln_matmul_reference(lx, lg, lb, lw, lbias)
    ok &= check("ln_matmul fwd", got, want, 3e-2)

    def ln_loss(fn):
        def go(x, g, b, w, bias):
            y = fn(x, g, b, w, bias)
            return (y.astype(jnp.float32) ** 2).mean()
        return go

    got_g = jax.jit(jax.grad(ln_loss(ln_matmul), argnums=(0, 1, 2, 3, 4))
                    )(lx, lg, lb, lw, lbias)
    want_g = jax.grad(ln_loss(ln_matmul_reference), argnums=(0, 1, 2, 3, 4)
                      )(lx, lg, lb, lw, lbias)
    for nm, g, wn in zip(("dx", "dgamma", "dbeta", "dw", "dbias"),
                         got_g, want_g):
        ok &= check_scaled(f"ln_matmul grad {nm}", jnp.reshape(g, (-1,)),
                           jnp.reshape(wn, (-1,)), 5e-2)

    def compare_models(tag, loss_f, loss_std, params, fwd_tol, grad_tol):
        """Fused-vs-standard twin comparison: jitted scalar loss + the
        gradient pytree compared PER LEAF under the max-normalized
        metric — a globally-raveled comparison would let large embedding
        grads mask a broken small-magnitude leaf (dgamma/dbeta)."""
        lf_val, gf = jax.jit(jax.value_and_grad(loss_f))(params)
        ls_val, gs = jax.jit(jax.value_and_grad(loss_std))(params)
        res = check_scaled(f"{tag} fwd", lf_val, ls_val, fwd_tol)
        gf, gs = jax.device_get((gf, gs))
        # Per-leaf scale, floored at 1% of the global max: a broken leaf
        # whose true magnitude is within 100x of the dominant one still
        # fails loudly, while structurally-degenerate leaves (key biases —
        # softmax is shift-invariant in k, so their true grad is pure
        # cancellation noise) aren't amplified into false alarms.
        global_max = max(
            float(np.max(np.abs(np.asarray(l, np.float32))))
            for l in jax.tree.leaves(gs)
        )
        if global_max == 0.0:
            print(f"FAIL {tag} grad: every oracle leaf is all-zero "
                  "(degenerate params?) — comparison would be vacuous")
            return False
        worst_err, worst_leaf, leaf_ok = 0.0, "?", True
        for (path, lf), (_, ls) in zip(
            jax.tree_util.tree_leaves_with_path(gf),
            jax.tree_util.tree_leaves_with_path(gs),
        ):
            g, w = np.asarray(lf, np.float32), np.asarray(ls, np.float32)
            scale = max(float(np.max(np.abs(w))), 1e-2 * global_max)
            err = float(np.max(np.abs(g - w))) / scale
            if err > worst_err:
                worst_err, worst_leaf = err, jax.tree_util.keystr(path)
            leaf_ok &= err <= grad_tol
        print(f"{'ok ' if leaf_ok else 'FAIL'} {tag} grad: worst leaf "
              f"{worst_leaf} scaled_err={worst_err:.2e} (tol {grad_tol})")
        return res & leaf_ok

    # fused vs unfused pre-LN transformer twins (compiled), fwd + grad.
    # f32 is the correctness gate (a wrong backward shows up at O(1));
    # bf16 is the integration smoke test — its loose tol absorbs
    # rounding-path divergence (both paths correct to bf16, different
    # rounding order) amplified by cancellation in small leaves.
    from distributed_tensorflow_tpu.models import transformer as tfm

    for tdt, tf_fwd, tf_grad in (("float32", 1e-2, 2e-2),
                                 ("bfloat16", 3e-2, 2.5e-1)):
        tkw = dict(vocab_size=256, max_len=128, num_layers=2, d_model=128,
                   num_heads=4, d_ff=256, dropout=0.0, causal=True,
                   pre_ln=True, dtype=tdt)
        t_std = tfm.Transformer(tfm.TransformerConfig(**tkw))
        t_f = tfm.Transformer(
            tfm.TransformerConfig(fused_ln_matmul=True, **tkw))
        ids = jnp.asarray(r.randint(0, 256, (4, 128)), jnp.int32)
        tparams = t_std.init(jax.random.PRNGKey(1), ids,
                             train=False)["params"]

        def lm_loss(m):
            def go(p):
                logits = m.apply({"params": p}, ids, train=False)
                return (logits.astype(jnp.float32) ** 2).mean()
            return go

        ok &= compare_models(f"transformer fused-LN [{tdt}]", lm_loss(t_f),
                             lm_loss(t_std), tparams, tf_fwd, tf_grad)

    # one fused bottleneck vs the standard flax block, train fwd + grad
    from distributed_tensorflow_tpu.models import common
    from distributed_tensorflow_tpu.models.resnet import ResNet50, ResNetConfig

    for rdt, r_fwd, r_grad in (("float32", 1e-2, 2e-2),
                               ("bfloat16", 3e-2, 2.5e-1)):
        kw = dict(stage_sizes=(1,), width=16, num_classes=10, dtype=rdt)
        m_std = ResNet50(ResNetConfig(**kw))
        m_f = ResNet50(ResNetConfig(block_impl="fused", **kw))
        params, mstate = common.make_init_fn(m_std, (32, 32, 3))(
            jax.random.PRNGKey(0)
        )
        # Perturb away from init: the zero-init bn3 gamma (resnet.py:84)
        # makes every upstream grad in the residual branch exactly zero at
        # init, so the per-leaf comparison would be vacuous there.
        leaves, treedef = jax.tree.flatten(params)
        keys = jax.random.split(jax.random.PRNGKey(7), len(leaves))
        params = jax.tree.unflatten(treedef, [
            l + 0.05 * jax.random.normal(k, l.shape, l.dtype)
            for l, k in zip(leaves, keys)
        ])
        xb = jnp.asarray(r.randn(8, 32, 32, 3), jnp.float32)

        def loss_model(m):
            def go(p):
                out, _ = m.apply({"params": p, **mstate}, xb, train=True,
                                 mutable=["batch_stats"])
                return (out.astype(jnp.float32) ** 2).mean()
            return go

        ok &= compare_models(f"resnet fused-block [{rdt}]", loss_model(m_f),
                             loss_model(m_std), params, r_fwd, r_grad)

    ok &= bench_shape_sweep(r)

    print("ALL OK" if ok else "FAILURES", flush=True)
    raise SystemExit(0 if ok else 1)


if __name__ == "__main__":
    main()
