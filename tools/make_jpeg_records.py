#!/usr/bin/env python
"""Convert an ImageFolder-layout directory (class_name/xxx.jpg) into the
framework's JPEG record pair (<out>.dat + <out>.idx — see
data/jpeg_records.py) by RAW BYTE CONCATENATION: original JPEG streams
are copied verbatim, never decoded or re-encoded, so conversion is
IO-bound and lossless. Labels are the sorted class-directory index
(torchvision ImageFolder convention); a <out>.classes.json sidecar
records the mapping.

The reference's equivalent step was building per-worker TFRecords of
JPEG bytes for tf.data (SURVEY.md §2a 'Input pipeline').

Usage:
  tools/make_jpeg_records.py /data/imagenet/train /data/records/train \
      [--shuffle-seed 0] [--limit N]
"""

import argparse
import json
import os
import sys
from itertools import zip_longest

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from distributed_tensorflow_tpu.data.jpeg_records import _ENTRY

_EXTS = (".jpg", ".jpeg")


def _class_files(class_dir: str) -> tuple[list[str], int]:
    """All JPEGs under a class dir (recursive, case-insensitive extension
    match — the torchvision ImageFolder contract) + skipped-file count."""
    kept, skipped = [], 0
    for root, _, names in sorted(os.walk(class_dir)):
        for f in sorted(names):
            if f.lower().endswith(_EXTS):
                kept.append(os.path.join(root, f))
            else:
                skipped += 1
    return kept, skipped


def convert(src: str, out: str, shuffle_seed: int | None = 0,
            limit: int | None = None) -> int:
    if limit is not None and limit <= 0:
        raise ValueError(f"--limit must be positive, got {limit}")
    classes = sorted(
        d for d in os.listdir(src)
        if os.path.isdir(os.path.join(src, d))
    )
    if not classes:
        raise ValueError(f"no class subdirectories under {src}")
    files, skipped = [], 0
    for label, c in enumerate(classes):
        kept, skip = _class_files(os.path.join(src, c))
        files.extend((p, label) for p in kept)
        skipped += skip
    if skipped:
        print(f"note: skipped {skipped} non-JPEG files", file=sys.stderr)
    if not files:
        raise ValueError(f"no .jpg/.jpeg files under {src}")
    if shuffle_seed is not None:
        # pre-shuffle so sequential readers of the .dat stream well even
        # before the per-epoch index shuffle kicks in
        np.random.RandomState(shuffle_seed).shuffle(files)
    elif limit is not None and limit < len(files):
        # --no-shuffle + --limit on the label-major list would truncate
        # to the first class(es) only; interleave round-robin per class
        # so the subset keeps every class represented
        by_label: dict[int, list] = {}
        for p, label in files:
            by_label.setdefault(label, []).append((p, label))
        files = [
            pair
            for tier in zip_longest(*by_label.values())
            for pair in tier if pair is not None
        ]
    if limit is not None:
        files = files[:limit]
    entries = np.empty(len(files), _ENTRY)
    off = 0
    with open(out + ".dat", "wb") as dat:
        for i, (path, label) in enumerate(files):
            with open(path, "rb") as f:
                raw = f.read()
            dat.write(raw)
            entries[i] = (off, len(raw), label)
            off += len(raw)
    entries.tofile(out + ".idx")
    with open(out + ".classes.json", "w") as f:
        json.dump(classes, f)
    print(f"{len(files)} images, {len(classes)} classes, "
          f"{off / 1e9:.2f} GB -> {out}.dat/.idx", file=sys.stderr)
    return len(files)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("src")
    ap.add_argument("out")
    ap.add_argument("--shuffle-seed", type=int, default=0)
    ap.add_argument("--no-shuffle", action="store_true")
    ap.add_argument("--limit", type=int, default=None)
    args = ap.parse_args()
    try:
        convert(args.src, args.out,
                shuffle_seed=None if args.no_shuffle else args.shuffle_seed,
                limit=args.limit)
    except ValueError as e:
        raise SystemExit(str(e))


if __name__ == "__main__":
    main()
