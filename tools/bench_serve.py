#!/usr/bin/env python
"""Serving micro-bench: decode throughput + batch occupancy, CPU-runnable.

Drives a ServeEngine over a queued request stream (more requests than
decode slots, the regime continuous batching exists for) on a tiny
random-weight decoder and reports from the engine's obs registry
(reset after warmup, so compile time never pollutes a percentile):

- ``tokens_per_sec``     — generated tokens / wall time (post-warmup)
- ``ttft_p50_ms/p99``    — submit → first token percentiles
- ``tpot_p50_ms/p99``    — mean per-output-token decode latency
- ``queue_wait_p50_ms``  — submit → slot admission
- ``mean_occupancy``     — mean active-slots / num_slots over decode steps
- ``full_batch_steps``   — steps that decoded with every slot live
- ``full_batch_frac``    — the acceptance gate: with a backlog queued,
                           the scheduler must keep the decode batch full
                           (ISSUE 1 acceptance criterion)

Usage:
    JAX_PLATFORMS=cpu python tools/bench_serve.py
    python tools/bench_serve.py --requests 32 --slots 8 --json out.json
"""

import argparse
import json
import random
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--max-new", type=int, default=24)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--json", type=str, default=None,
                    help="also write the result dict to this path")
    args = ap.parse_args(argv)

    from distributed_tensorflow_tpu import serve
    from distributed_tensorflow_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(
        vocab_size=256, max_len=128, num_layers=2, d_model=64, num_heads=4,
        d_ff=128, dropout=0.0, dtype="float32", causal=True, pre_ln=True,
    )
    eng = serve.ServeEngine.with_random_params(
        cfg, seed=args.seed, num_slots=args.slots
    )

    rng = random.Random(args.seed)
    prompts = [
        [rng.randrange(cfg.vocab_size) for _ in range(rng.randint(4, 16))]
        for _ in range(args.requests)
    ]

    # warmup on the SAME engine: jit tracing is cached per wrapper, so a
    # fresh ServeEngine would recompile inside the timed loop. Hit the
    # decode step and every prefill bucket the stream will use, drain,
    # then time (warmup requests are drained out of the stats entirely).
    for b in sorted({serve.prefill_bucket(len(p)) for p in prompts}):
        eng.submit([rng.randrange(cfg.vocab_size) for _ in range(b)],
                   max_new_tokens=2)
    eng.run()
    eng.registry.reset()  # drop warmup/compile observations

    for p in prompts:
        eng.submit(p, max_new_tokens=args.max_new)

    t0 = time.perf_counter()
    stats = []
    while eng.sched.has_work:
        stats.append(eng.step())
    wall = time.perf_counter() - t0

    from distributed_tensorflow_tpu.obs import goodput

    reg = eng.registry
    ttft = reg.get("serve_ttft_seconds")
    tokens = int(reg.get("serve_tokens_total").value)
    finished = int(sum(
        m.value for m in reg.collect() if m.name == "serve_finished_total"
    ))
    assert ttft.count == finished == args.requests, (
        f"telemetry mismatch: ttft={ttft.count} finished={finished} "
        f"submitted={args.requests}"
    )

    decode_steps = [s for s in stats if s.decoded_slots]
    full = sum(1 for s in decode_steps if s.occupancy == 1.0)
    # percentile read-back via the SHARED helper (obs/goodput.py): one
    # formula for the printed numbers and any registry consumer
    pct = lambda name, qs=(0.5, 0.99): goodput.latency_percentiles_ms(  # noqa: E731
        reg, name, quantiles=qs)
    ttft_ms = pct("serve_ttft_seconds")
    tpot_ms = pct("serve_tpot_seconds")
    qwait_ms = pct("serve_queue_wait_seconds", (0.5,))
    from distributed_tensorflow_tpu.obs import scaling

    # provenance block (obs/scaling.py): every serve-bench row carries
    # its backend context, same stamp as bench.py / tools/sweep.py
    result = scaling.stamp_provenance({
        "requests": args.requests,
        "slots": args.slots,
        "steps": len(stats),
        "generated_tokens": tokens,
        "wall_s": round(wall, 3),
        "tokens_per_sec": round(tokens / wall, 1),
        "ttft_p50_ms": ttft_ms["p50_ms"],
        "ttft_p99_ms": ttft_ms["p99_ms"],
        "tpot_p50_ms": tpot_ms["p50_ms"],
        "tpot_p99_ms": tpot_ms["p99_ms"],
        "queue_wait_p50_ms": qwait_ms["p50_ms"],
        "mean_occupancy": round(
            sum(s.occupancy for s in decode_steps) / len(decode_steps), 3
        ),
        "full_batch_steps": full,
        "full_batch_frac": round(full / len(decode_steps), 3),
    })
    # Chaos epilogue (ISSUE 3 acceptance): exercise the timeout and
    # cancel eviction paths on the SAME engine and re-check the
    # histogram-counts == Σ serve_finished_total invariant with the new
    # reasons in play. Runs after percentiles were read, so the two
    # aborted requests never pollute the steady-state numbers above.
    doomed = eng.submit([1, 2, 3], max_new_tokens=4, deadline_s=1e-9)
    while doomed not in eng.sched.finished:
        eng.step()
    killed = eng.submit([4, 5], max_new_tokens=4)
    assert eng.cancel(killed)
    eng.run()
    from distributed_tensorflow_tpu.serve import scheduler as sl

    reasons = {
        dict(m.labels)["reason"]: int(m.value)
        for m in reg.collect() if m.name == "serve_finished_total"
    }
    total = sum(reasons.values())
    assert reasons[sl.FINISH_TIMEOUT] >= 1 and reasons[sl.FINISH_CANCELLED] >= 1
    assert reg.get("serve_ttft_seconds").count == total, (
        f"ttft count {reg.get('serve_ttft_seconds').count} != finished {total} "
        f"after timeout/cancel evictions ({reasons})"
    )
    assert reg.get("serve_tpot_seconds").count == total, (
        f"tpot count != finished after timeout/cancel evictions ({reasons})"
    )

    print(json.dumps(result, indent=2))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(result, f, indent=2)
    if result["full_batch_steps"] == 0:
        print("FAIL: never sustained a full decode batch", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
