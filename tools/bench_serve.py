#!/usr/bin/env python
"""Serving micro-bench: decode throughput + batch occupancy, CPU-runnable.

Drives a ServeEngine over a queued request stream (more requests than
decode slots, the regime continuous batching exists for) on a tiny
random-weight decoder and reports from the engine's obs registry
(reset after warmup, so compile time never pollutes a percentile):

- ``tokens_per_sec``     — generated tokens / wall time (post-warmup)
- ``ttft_p50_ms/p99``    — submit → first token percentiles
- ``tpot_p50_ms/p99``    — mean per-output-token decode latency
- ``queue_wait_p50_ms``  — submit → slot admission
- ``mean_occupancy``     — mean working-slots / num_slots over steps
- ``full_batch_frac``    — the acceptance gate: with a backlog queued,
                           the scheduler must keep the batch full
                           (``full_batch_frac_backlog`` restricts the
                           denominator to steps that HAD a backlog)

Presets:

- ``steady`` (default) — uniform short prompts, the PR-1 throughput rig.
- ``chaos``  — the paged-cache acceptance rig (ISSUE 13): short/long
  mixed traffic behind a shared system prefix (the seeded chaos-stream
  idiom of resilience/faults.py), a slice of requests carrying
  deadlines, seeded mid-flight cancels, and a KV-footprint report:
  measured KV bytes per resident request (paged: blocks actually held ×
  block bytes) against the dense layout's per-slot ``max_len`` row,
  plus prefix-reuse hits and the per-step starvation bound (no resident
  decoder goes more than one step between tokens — chunked prefill
  interleaves instead of stalling the batch).

Both presets end with the chaos epilogue (timeout + cancel on the SAME
engine, re-checking histogram-counts == Σ serve_finished_total), and a
paged engine must shut down leak-free: after ``drain()`` the block
allocator is back to all-free. ``--parity-check`` additionally gates
64-token greedy parity of the paged path against the dense fallback on
the same weights (the ci_fast.sh smoke runs it).

``--fleet N`` lifts the chaos preset to the serve-fleet tier
(docs/serving.md "Serve fleet"): an OPEN-LOOP trace — seeded arrival
times, shared-system-prompt prefix groups, interactive/batch lanes —
driven twice through N in-process replicas (``LocalReplica``) behind
the router, once with ``policy="prefix"`` and once with the seeded
random baseline, same trace, same mid-run replica kill (chaos preset).
Reports per-lane p50/p99 TTFT/TPOT from the ROUTER's registry (client
clocks, accumulated across the kill and requeues) and the
routed-vs-random prefix-hit comparison, with gates: every request
finishes, every surviving replica drains leak-free, and routed
prefix-reuse strictly beats random.

Usage:
    JAX_PLATFORMS=cpu python tools/bench_serve.py
    python tools/bench_serve.py --preset chaos --requests 24 --json out.json
    python tools/bench_serve.py --dense   # the PR-1 slot-dense cache
    python tools/bench_serve.py --preset chaos --fleet 3 --requests 24
"""

import argparse
import json
import random
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def _make_engine(cfg, serve, args, seed):
    return serve.ServeEngine.with_random_params(
        cfg, seed=seed, num_slots=args.slots, paged=not args.dense,
        block_size=args.block_size, num_blocks=args.blocks,
        prefill_chunk=args.prefill_chunk,
        prefix_reuse=not args.no_reuse,
        spec_k=args.spec_k, paged_impl=args.paged_impl,
    )


def _parity_check(cfg, serve, args):
    """Greedy decode must be token-identical through every serve path —
    the bench-side twin of the test-suite gates: 64 steps dense ==
    paged-gather == paged-fused, and speculative == non-speculative on
    both a short and a multi-chunk-long prompt."""
    import jax

    from distributed_tensorflow_tpu.models import transformer as tfm

    model = tfm.Transformer(cfg)
    params, _ = tfm.make_init_fn(model, 8)(jax.random.PRNGKey(args.seed))
    prompt = [5, 17, 3, 99, 42, 7, 11]
    long_prompt = [(i * 7 + 3) % cfg.vocab_size
                   for i in range(3 * args.prefill_chunk + 5)]
    dense = serve.ServeEngine(cfg, params, num_slots=1, paged=False)
    want = list(dense.stream(prompt, max_new_tokens=64))

    def paged_stream(p, **kw):
        eng = serve.ServeEngine(
            cfg, params, num_slots=1, paged=True,
            block_size=args.block_size, prefill_chunk=args.prefill_chunk,
            **kw)
        got = list(eng.stream(p, max_new_tokens=64))
        eng.drain()
        assert eng.alloc.blocks_free == eng.cache.num_blocks, \
            f"parity engine leaked blocks ({kw})"
        return got

    for impl in ("gather", "fused"):
        got = paged_stream(prompt, paged_impl=impl)
        assert got == want, (
            f"paged[{impl}]/dense greedy divergence at step "
            f"{next(i for i, (a, b) in enumerate(zip(got, want)) if a != b)}"
        )
    want_long = paged_stream(long_prompt)
    for p, w in ((prompt, want), (long_prompt, want_long)):
        got = paged_stream(p, spec_k=4)
        assert got == w, (
            f"spec/non-spec greedy divergence (P={len(p)}) at step "
            f"{next(i for i, (a, b) in enumerate(zip(got, w)) if a != b)}"
        )
    print("parity-check: 64-step dense == paged[gather] == paged[fused]; "
          "spec == non-spec (short + long)", file=sys.stderr)


def _fleet_trace(cfg, args, rng):
    """Seeded open-loop trace: ``(t_arrival, prompt, lane, prefix_len)``
    rows with the chaos length mix behind per-group shared system
    prompts. Arrival times are fixed up front — the trace never reacts
    to completions, which is what makes a queueing tail honest."""
    from distributed_tensorflow_tpu import serve

    groups = [[rng.randrange(cfg.vocab_size) for _ in range(24)]
              for _ in range(args.prefix_groups)]
    long_hi = max(cfg.max_len - 24 - args.max_new - 1, 17)
    trace, t = [], 0.0
    for _ in range(args.requests):
        t += rng.uniform(0.0, 2 * args.arrival_ms / 1e3)
        g = rng.randrange(len(groups))
        if rng.random() < 0.6:
            body = rng.randint(4, 16)
        else:
            body = rng.randint(min(40, long_hi), long_hi)
        prompt = groups[g] + [rng.randrange(cfg.vocab_size)
                              for _ in range(body)]
        lane = (serve.LANE_INTERACTIVE if rng.random() < 0.5
                else serve.LANE_BATCH)
        trace.append((t, prompt, lane, len(groups[g])))
    return trace


def _run_fleet(cfg, serve, args, trace, policy, kill_after,
               trace_dir=None):
    """Drive one fleet over the trace; kill one busy replica once
    ``kill_after`` requests have finished (None = no chaos). Returns
    the per-run report fragment. With ``trace_dir`` every process-role
    keeps a request ledger (obs/reqtrace.py) — router plus one per
    replica incarnation — dumped there for tools/trace_view.py."""
    from distributed_tensorflow_tpu.obs.flightrec import FlightRecorder
    from distributed_tensorflow_tpu.obs.registry import Registry
    from distributed_tensorflow_tpu.obs.reqtrace import ReqTrace

    reg, rec = Registry(), FlightRecorder(capacity=4096)
    engines = []
    traces = []  # (filename, ReqTrace) to dump after the run

    router_trace = None
    if trace_dir is not None:
        router_trace = ReqTrace(src="router")
        traces.append(("reqtrace-router.jsonl", router_trace))

    def launch(index, incarnation):
        eng_trace = None
        if trace_dir is not None:
            eng_trace = ReqTrace(src=f"w{index}i{incarnation}")
            traces.append(
                (f"reqtrace-w{index}i{incarnation}.jsonl", eng_trace))
        eng = serve.ServeEngine.with_random_params(
            cfg, seed=args.seed, num_slots=args.slots, paged=True,
            block_size=args.block_size, num_blocks=args.blocks,
            prefill_chunk=args.prefill_chunk, reqtrace=eng_trace)
        engines.append(eng)
        return serve.LocalReplica(eng)

    router = serve.Router(policy=policy, max_outstanding=args.slots,
                          seed=args.seed, registry=reg, flightrec=rec,
                          reqtrace=router_trace)
    sup = serve.ServeFleetSupervisor(
        launch, args.fleet, router=router, registry=reg, flightrec=rec,
        sleep=lambda s: None)
    sup.start()

    t0 = time.perf_counter()
    i, killed = 0, kill_after is None
    while i < len(trace) or not router.idle:
        now = time.perf_counter() - t0
        while i < len(trace) and trace[i][0] <= now:
            _, prompt, lane, plen = trace[i]
            router.submit(prompt, max_new_tokens=args.max_new,
                          lane=lane, prefix_len=plen)
            i += 1
        if not killed and len(router.finished) >= kill_after \
                and len(sup.replicas) > 1:
            # prefer a victim with streams in flight: the kill must
            # cost something, or the requeue path went unexercised
            busy = [w for w in sorted(sup.replicas)
                    if router.outstanding.get(w)]
            victim = busy[0] if busy else min(sup.replicas)
            sup.replicas[victim].handle.hard_kill()
            killed = True
        sup.pump()
    wall = time.perf_counter() - t0
    sup.stop()

    if trace_dir is not None:
        import os

        os.makedirs(trace_dir, exist_ok=True)
        for name, rt in traces:
            rt.dump(os.path.join(trace_dir, name),
                    reason=f"bench_serve_{policy}")

    from distributed_tensorflow_tpu.obs import goodput

    assert len(router.finished) == args.requests, (
        f"lost requests: {len(router.finished)}/{args.requests} finished"
    )
    leaked = [i for i, d in sup.drained.items() if not d.get("leak_free")]
    assert not leaked, f"replicas leaked blocks after drain: {leaked}"

    lanes = {}
    for lane in serve.LANES:
        n = reg.get("router_ttft_seconds", lane=lane).count
        if not n:
            lanes[lane] = None
            continue
        ttft = goodput.latency_percentiles_ms(
            reg, "router_ttft_seconds", lane=lane)
        row = {"finished": n,
               "ttft_p50_ms": ttft["p50_ms"], "ttft_p99_ms": ttft["p99_ms"]}
        if reg.get("router_tpot_seconds", lane=lane).count:
            tpot = goodput.latency_percentiles_ms(
                reg, "router_tpot_seconds", lane=lane)
            row.update(tpot_p50_ms=tpot["p50_ms"],
                       tpot_p99_ms=tpot["p99_ms"])
        lanes[lane] = row
    tokens = sum(len(r.delivered) for r in router.finished.values())
    return {
        "policy": policy,
        "wall_s": round(wall, 3),
        "generated_tokens": tokens,
        "tokens_per_sec": round(tokens / wall, 1) if wall else None,
        "lanes": lanes,
        "requeues": int(reg.get("router_requeues_total").value),
        "replica_deaths": int(
            reg.get("serve_replica_deaths_total").value),
        "router_prefix_hits": int(
            reg.get("router_prefix_hits_total").value),
        # ground truth on the engines: blocks actually mapped from the
        # shared-prefix cache instead of being re-prefilled
        "engine_prefix_reuse_hits": sum(
            int(e.registry.get("prefix_reuse_hits_total").value)
            for e in engines),
    }


def _fleet_bench(cfg, serve, args):
    from distributed_tensorflow_tpu.obs import scaling

    rng = random.Random(args.seed)
    trace = _fleet_trace(cfg, args, rng)
    # compile outside the timed runs: the jitted chunk/decode/copy
    # programs are cached per shape process-wide, so one throwaway
    # engine warms every replica of both runs
    warm = serve.ServeEngine.with_random_params(
        cfg, seed=args.seed, num_slots=args.slots, paged=True,
        block_size=args.block_size, num_blocks=args.blocks,
        prefill_chunk=args.prefill_chunk)
    wp = [rng.randrange(cfg.vocab_size) for _ in range(2 * args.block_size)]
    for _ in range(2):
        warm.submit(wp, max_new_tokens=2)
        warm.run()
    warm.drain()

    kill_after = args.requests // 2 if args.preset == "chaos" else None
    # only the routed (headline) run is traced: the random baseline is
    # a comparison control, not a latency story anyone debugs
    routed = _run_fleet(cfg, serve, args, trace, "prefix", kill_after,
                        trace_dir=args.trace)
    rand = _run_fleet(cfg, serve, args, trace, "random", kill_after)

    result = scaling.stamp_provenance({
        "preset": args.preset,
        "fleet": args.fleet,
        "requests": args.requests,
        "slots": args.slots,
        "prefix_groups": args.prefix_groups,
        "arrival_ms": args.arrival_ms,
        "kill_after": kill_after,
        "routed": routed,
        "random": rand,
        "prefix_hit_advantage": (routed["engine_prefix_reuse_hits"]
                                 - rand["engine_prefix_reuse_hits"]),
    })
    print(json.dumps(result, indent=2))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(result, f, indent=2)
    if args.preset == "chaos" and routed["requeues"] < 1:
        print("FAIL: chaos kill exercised no requeue", file=sys.stderr)
        return 1
    if routed["engine_prefix_reuse_hits"] <= rand["engine_prefix_reuse_hits"]:
        print(f"FAIL: prefix-aware routing did not beat random "
              f"({routed['engine_prefix_reuse_hits']} <= "
              f"{rand['engine_prefix_reuse_hits']} reuse hits)",
              file=sys.stderr)
        return 1
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--max-new", type=int, default=24)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--preset", choices=("steady", "chaos"),
                    default="steady")
    ap.add_argument("--dense", action="store_true",
                    help="PR-1 slot-dense cache (the exact-parity "
                         "fallback) instead of the paged pool")
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--blocks", type=int, default=None,
                    help="pool size (default: dense-equivalent capacity)")
    ap.add_argument("--prefill-chunk", type=int, default=32)
    ap.add_argument("--no-reuse", action="store_true",
                    help="disable copy-on-write prefix reuse")
    ap.add_argument("--spec-k", type=int, default=0,
                    help="draft tokens per speculative verify step "
                         "(0 = plain one-token decode)")
    ap.add_argument("--paged-impl", default=None,
                    choices=("auto", "gather", "fused", "pallas"),
                    help="paged-attention dispatch "
                         "(ops.attention.paged_attention)")
    ap.add_argument("--compare-baseline", action="store_true",
                    help="also time the same workload through the "
                         "gather-path non-speculative engine and report "
                         "speedup_vs_gather_baseline")
    ap.add_argument("--min-speedup", type=float, default=None,
                    help="with --compare-baseline: fail unless "
                         "speedup_vs_gather_baseline >= this")
    ap.add_argument("--parity-check", action="store_true",
                    help="gate 64-step greedy parity paged vs dense")
    ap.add_argument("--json", type=str, default=None,
                    help="also write the result dict to this path")
    ap.add_argument("--fleet", type=int, default=0, metavar="N",
                    help="drive N serve replicas behind the router "
                         "instead of one engine (open-loop trace, "
                         "routed-vs-random comparison)")
    ap.add_argument("--prefix-groups", type=int, default=3,
                    help="shared system prompts in the fleet trace")
    ap.add_argument("--arrival-ms", type=float, default=2.0,
                    help="mean interarrival of the open-loop trace")
    ap.add_argument("--trace", type=str, default=None, metavar="DIR",
                    help="with --fleet: dump per-process request-trace "
                         "ledgers (dtf-reqtrace-1) for the routed run "
                         "here, for tools/trace_view.py")
    args = ap.parse_args(argv)
    if args.dense and args.spec_k:
        ap.error("--spec-k requires the paged engine; drop --dense")
    if args.min_speedup is not None and not args.compare_baseline:
        ap.error("--min-speedup needs --compare-baseline")
    if args.fleet and args.dense:
        ap.error("--fleet drives paged replicas; drop --dense")
    if args.trace and not args.fleet:
        ap.error("--trace records the fleet's request ledger; add --fleet")

    from distributed_tensorflow_tpu import serve
    from distributed_tensorflow_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(
        vocab_size=256, max_len=128, num_layers=2, d_model=64, num_heads=4,
        d_ff=128, dropout=0.0, dtype="float32", causal=True, pre_ln=True,
    )
    if args.parity_check:
        _parity_check(cfg, serve, args)
    if args.fleet:
        return _fleet_bench(cfg, serve, args)
    eng = _make_engine(cfg, serve, args, args.seed)

    rng = random.Random(args.seed)
    sys_prefix = [rng.randrange(cfg.vocab_size) for _ in range(24)]
    if args.preset == "chaos":
        # mixed-length stream behind one shared system prefix: the
        # short/long mix is what a dense cache wastes max_len rows on
        prompts, deadlines = [], []
        # keep "long" strictly longer than the short band even when a
        # large --max-new squeezes the headroom (never a silent
        # degenerate range)
        long_hi = max(cfg.max_len - len(sys_prefix) - args.max_new - 1, 17)
        for _ in range(args.requests):
            if rng.random() < 0.6:
                body = rng.randint(4, 16)
            else:
                body = rng.randint(min(40, long_hi), long_hi)
            prompts.append(
                sys_prefix + [rng.randrange(cfg.vocab_size)
                              for _ in range(body)])
            deadlines.append(rng.uniform(0.5, 2.0)
                             if rng.random() < 0.2 else None)
    else:
        prompts = [
            [rng.randrange(cfg.vocab_size) for _ in range(rng.randint(4, 16))]
            for _ in range(args.requests)
        ]
        deadlines = [None] * args.requests

    # warmup on the SAME engine: jit tracing is cached per wrapper, so a
    # fresh ServeEngine would recompile inside the timed loop. The paged
    # path compiles one chunk/decode/verify program per block-table
    # bucket (the engine trims the table to the widest live slot,
    # power-of-two widths); the dense path needs every prefill bucket
    # the stream will use. Warmup requests drain out of the stats
    # entirely.
    def _warm_paged(e):
        # two identical full-block prompts back to back: the second
        # matches the first's cached blocks and its capped last-position
        # rewrite triggers a copy-on-write, so copy_block compiles
        # during warmup too, not inside the timed loop
        wp = [rng.randrange(cfg.vocab_size)
              for _ in range(2 * args.block_size)]
        for _ in range(2):
            e.submit(wp, max_new_tokens=2)
            e.run()
        # touch every table bucket so no prefill/decode/verify program
        # compiles inside the timed loop
        L = 1
        while True:
            P = min(L * args.block_size - 2, cfg.max_len - 4)
            e.submit([rng.randrange(cfg.vocab_size) for _ in range(P)],
                     max_new_tokens=2)
            e.run()
            if P >= cfg.max_len - 4:
                break
            L *= 2
        # keep measured reuse honest: drop what warmup cached
        e.alloc.flush_prefix_cache()

    if args.dense:
        for b in sorted({serve.prefill_bucket(len(p)) for p in prompts}):
            eng.submit([rng.randrange(cfg.vocab_size) for _ in range(b)],
                       max_new_tokens=2)
        eng.run()
    else:
        # two identical full-block prompts back to back: the second
        # matches the first's cached blocks and its capped last-position
        # rewrite triggers a copy-on-write, so copy_block compiles
        # during warmup too, not inside the timed loop
        _warm_paged(eng)
    eng.registry.reset()  # drop warmup/compile observations
    # cow_copies lives on the allocator, not the registry: snapshot it
    # here so the report counts only the measured window, like the
    # registry-sourced counters beside it
    cow_at_reset = 0 if args.dense else eng.alloc.cow_copies

    uids = [eng.submit(p, max_new_tokens=args.max_new, deadline_s=dl)
            for p, dl in zip(prompts, deadlines)]
    # seeded mid-flight cancels (chaos): step index → victim uid
    cancel_at = ({rng.randrange(2, 40): rng.choice(uids)
                  for _ in range(2)}
                 if args.preset == "chaos" else {})

    t0 = time.perf_counter()
    stats = []
    kv_samples = []  # (blocks_in_use, residents) per decode step
    backlog = []     # queue non-empty at step start?
    last_seen: dict[int, int] = {}
    max_gap = 0
    while eng.sched.has_work:
        step_i = len(stats)
        if step_i in cancel_at:
            eng.cancel(cancel_at[step_i])
        backlog.append(bool(eng.sched.queue))
        st = eng.step()
        stats.append(st)
        residents = len(eng.sched.active_slots())
        if st.decoded_slots and not args.dense:
            kv_samples.append((eng.alloc.blocks_in_use, residents))
        for uid, _tok in st.tokens:
            if uid in last_seen:
                max_gap = max(max_gap, step_i - last_seen[uid])
            last_seen[uid] = step_i
    wall = time.perf_counter() - t0

    # same-run baseline: the SAME workload through the PR-13
    # gather-then-attend path with speculation off — the denominator of
    # the perf-regression story, measured under identical conditions so
    # host noise cancels instead of hiding in a stale reference number
    baseline_tps = None
    if args.compare_baseline and not args.dense:
        beng = serve.ServeEngine.with_random_params(
            cfg, seed=args.seed, num_slots=args.slots, paged=True,
            block_size=args.block_size, num_blocks=args.blocks,
            prefill_chunk=args.prefill_chunk,
            prefix_reuse=not args.no_reuse, paged_impl="gather")
        _warm_paged(beng)
        beng.registry.reset()
        for p, dl in zip(prompts, deadlines):
            beng.submit(p, max_new_tokens=args.max_new, deadline_s=dl)
        bt0 = time.perf_counter()
        while beng.sched.has_work:
            beng.step()
        bwall = time.perf_counter() - bt0
        btokens = int(beng.registry.get("serve_tokens_total").value)
        beng.drain()
        baseline_tps = round(btokens / bwall, 1) if bwall else None

    from distributed_tensorflow_tpu.obs import goodput

    reg = eng.registry
    ttft = reg.get("serve_ttft_seconds")
    tokens = int(reg.get("serve_tokens_total").value)
    finished = int(sum(
        m.value for m in reg.collect() if m.name == "serve_finished_total"
    ))
    assert ttft.count == finished == args.requests, (
        f"telemetry mismatch: ttft={ttft.count} finished={finished} "
        f"submitted={args.requests}"
    )

    decode_steps = [s for s in stats if s.decoded_slots]
    full = sum(1 for s in stats if s.occupancy == 1.0)
    backlog_steps = [s for s, b in zip(stats, backlog) if b]
    full_backlog = sum(1 for s in backlog_steps if s.occupancy == 1.0)
    # percentile read-back via the SHARED helper (obs/goodput.py): one
    # formula for the printed numbers and any registry consumer
    pct = lambda name, qs=(0.5, 0.99): goodput.latency_percentiles_ms(  # noqa: E731
        reg, name, quantiles=qs)
    ttft_ms = pct("serve_ttft_seconds")
    tpot_ms = pct("serve_tpot_seconds")
    qwait_ms = pct("serve_queue_wait_seconds", (0.5,))
    from distributed_tensorflow_tpu.obs import scaling

    # provenance block (obs/scaling.py): every serve-bench row carries
    # its backend context, same stamp as bench.py / tools/sweep.py
    result = scaling.stamp_provenance({
        "preset": args.preset,
        "kv_layout": "dense" if args.dense else "paged",
        "requests": args.requests,
        "slots": args.slots,
        "steps": len(stats),
        "generated_tokens": tokens,
        "wall_s": round(wall, 3),
        "tokens_per_sec": round(tokens / wall, 1),
        "ttft_p50_ms": ttft_ms["p50_ms"],
        "ttft_p99_ms": ttft_ms["p99_ms"],
        "tpot_p50_ms": tpot_ms["p50_ms"],
        "tpot_p99_ms": tpot_ms["p99_ms"],
        "queue_wait_p50_ms": qwait_ms["p50_ms"],
        "mean_occupancy": round(
            sum(s.occupancy for s in decode_steps) / len(decode_steps), 3
        ) if decode_steps else None,
        "full_batch_steps": full,
        "full_batch_frac": round(full / len(stats), 3),
        "full_batch_frac_backlog": round(
            full_backlog / len(backlog_steps), 3) if backlog_steps else None,
        # starvation bound: steps between consecutive tokens of one
        # request — chunked prefill must interleave, never stall decode
        "max_intertoken_steps": max_gap,
    })
    if not args.dense and kv_samples:
        # KV footprint: what a resident request actually costs, vs the
        # max_len row the dense layout would pin for it (kv_samples is
        # empty when every request finished at its prefill token — no
        # decode step ever sampled the pool)
        bpb = eng.cache.block_nbytes()
        # what the dense layout pins per resident: a full max_len row
        dense_per_req = bpb // args.block_size * cfg.max_len
        per_res = [u * bpb / r for u, r in kv_samples if r]
        result.update({
            "block_size": args.block_size,
            "num_blocks": eng.cache.num_blocks,
            "kv_block_bytes": bpb,
            "kv_blocks_peak": max(u for u, _ in kv_samples),
            "kv_bytes_per_resident_request": round(
                sum(per_res) / len(per_res)),
            "kv_bytes_per_request_dense": dense_per_req,
            "kv_bytes_saved_frac": round(
                1.0 - sum(per_res) / len(per_res) / dense_per_req, 3),
            "prefix_reuse_hits": int(
                reg.get("prefix_reuse_hits_total").value),
            "prefill_chunks": int(reg.get("prefill_chunks_total").value),
            "kv_block_evictions": int(
                reg.get("kv_block_evictions_total").value),
            "cow_copies": eng.alloc.cow_copies - cow_at_reset,
        })
    if not args.dense:
        result["paged_impl"] = args.paged_impl or "auto"
    if args.spec_k and not args.dense:
        result.update({
            "spec_k": args.spec_k,
            "spec_tokens_proposed": int(
                reg.get("spec_tokens_proposed_total").value),
            "spec_tokens_accepted": int(
                reg.get("spec_tokens_accepted_total").value),
            "spec_acceptance_rate": round(
                reg.get("spec_acceptance_rate").value, 3),
        })
    if baseline_tps is not None:
        result["baseline_gather_tokens_per_sec"] = baseline_tps
        result["speedup_vs_gather_baseline"] = round(
            result["tokens_per_sec"] / baseline_tps, 2)
    # Chaos epilogue (ISSUE 3 acceptance): exercise the timeout and
    # cancel eviction paths on the SAME engine and re-check the
    # histogram-counts == Σ serve_finished_total invariant with the new
    # reasons in play. Runs after percentiles were read, so the two
    # aborted requests never pollute the steady-state numbers above.
    doomed = eng.submit([1, 2, 3], max_new_tokens=4, deadline_s=1e-9)
    while doomed not in eng.sched.finished:
        eng.step()
    killed = eng.submit([4, 5], max_new_tokens=4)
    assert eng.cancel(killed)
    eng.run()
    from distributed_tensorflow_tpu.serve import scheduler as sl

    reasons = {
        dict(m.labels)["reason"]: int(m.value)
        for m in reg.collect() if m.name == "serve_finished_total"
    }
    total = sum(reasons.values())
    assert reasons[sl.FINISH_TIMEOUT] >= 1 and reasons[sl.FINISH_CANCELLED] >= 1
    assert reg.get("serve_ttft_seconds").count == total, (
        f"ttft count {reg.get('serve_ttft_seconds').count} != finished {total} "
        f"after timeout/cancel evictions ({reasons})"
    )
    assert reg.get("serve_tpot_seconds").count == total, (
        f"tpot count != finished after timeout/cancel evictions ({reasons})"
    )
    # leak gate: a drained paged engine hands EVERY block back
    eng.drain()
    if not args.dense:
        assert eng.alloc.blocks_free == eng.cache.num_blocks, (
            f"leaked blocks: {eng.alloc.blocks_in_use} still referenced "
            f"after drain"
        )
        result["leak_free_shutdown"] = True

    print(json.dumps(result, indent=2))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(result, f, indent=2)
    if result["full_batch_steps"] == 0:
        print("FAIL: never sustained a full decode batch", file=sys.stderr)
        return 1
    if args.preset == "chaos":
        frac = result["full_batch_frac_backlog"]
        if frac is not None and frac < 0.9:
            print(f"FAIL: full_batch_frac_backlog={frac} < 0.9 under "
                  f"chaos traffic", file=sys.stderr)
            return 1
        if result["max_intertoken_steps"] > 1 and not args.dense \
                and args.blocks is None:
            print(f"FAIL: a resident decoder starved for "
                  f"{result['max_intertoken_steps']} steps", file=sys.stderr)
            return 1
    if args.min_speedup is not None:
        sp = result.get("speedup_vs_gather_baseline")
        if sp is None or sp < args.min_speedup:
            print(f"FAIL: speedup_vs_gather_baseline={sp} < "
                  f"{args.min_speedup}", file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
