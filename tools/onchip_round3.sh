#!/bin/bash
# HISTORICAL (round-3 record; superseded by tools/onchip_round5.sh —
# new sessions go there, and scaling curves through tools/sweep.py,
# whose dtf-scaling-1 reports are provenance-stamped so a CPU fallback
# can never read as a TPU row again).
# Round-3 on-chip measurement session (VERDICT r2 items 1, 2, 5 + Weak #2).
# Same discipline as onchip_round2.sh: SEQUENTIAL (single device lease),
# failure-tolerant, one log per step. New vs round 2:
#   - HBM/MXU roofline microbench runs FIRST (the 445 GB/s re-measure)
#   - JPEG-decode-fed bench window (BENCH_DATA=jpeg)
# Usage: bash tools/onchip_round3.sh [outdir]   (default /tmp/onchip_r3)
set -u
OUT=${1:-/tmp/onchip_r3}
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

run() { # name timeout_s cmd...
  local name=$1 t=$2; shift 2
  echo "=== $name ($(date -u +%H:%M:%S)) ==="
  timeout --signal=TERM --kill-after=60 "$t" "$@" \
    >"$OUT/$name.log" 2>&1
  local rc=$?
  echo "    rc=$rc  tail:"
  tail -3 "$OUT/$name.log" | sed 's/^/    /'
  return $rc
}

# 0. cheap probe — bail early if the relay is down
run probe 180 python -u -c "
import jax, jax.numpy as jnp
print(jax.devices(), float(jax.jit(lambda a:(a@a).sum())(jnp.ones((256,256),jnp.bfloat16))))
" || { echo 'relay down; aborting session'; exit 1; }

# 1. roofline inputs: re-measure HBM bandwidth + MXU peak (Weak #2)
run hbm 600 python -u tools/bench_hbm.py

# 2. parity gate for every fused kernel (26 checks, compiled Mosaic)
run validate 900 python -u tools/validate_fused_tpu.py

# 3. flagship bench: fused default (auto-falls-back) then standard
run bench_fused 1200 python -u bench.py
run bench_standard 1200 env BENCH_BLOCK_IMPL=standard python -u bench.py

# 4. JPEG-decode-fed window (VERDICT item 2: decode inside a measured
#    TPU window, through the production JpegClassificationDataset path);
#    then the transfer-sync A/B for the round-2 0.044 fed anomaly
run bench_jpeg 1500 env BENCH_DATA=jpeg python -u bench.py
run bench_jpeg_putsync 1500 env BENCH_DATA=jpeg BENCH_PUT_SYNC=1 \
  python -u bench.py

# 5. kernel microbench at bench shapes (fwd then grad)
run microbench_fwd 900 python -u tools/bench_fused_kernels.py fwd 10
run microbench_grad 900 python -u tools/bench_fused_kernels.py grad 10

# 6. BERT-base MLM + GPT fused-LN ablation (first transformer numbers)
run bert 1200 python -u tools/bench_bert.py
run bert_dense_attn 1200 env BENCH_ATTN=dense python -u tools/bench_bert.py
run gpt_plain 1200 env BENCH_MODEL=gpt python -u tools/bench_bert.py
run gpt_fused_ln 1200 env BENCH_MODEL=gpt BENCH_FUSED_LN=1 \
  python -u tools/bench_bert.py

# 7. long-context: 4k flash-attention GPT (first long-context number;
#    SURVEY §5.7 — ring/SP path is multi-chip, this reads the single-chip
#    flash-attention memory/throughput point)
run gpt_long4k 1500 env BENCH_MODEL=gpt BENCH_SEQ=4096 BENCH_BATCH=8 \
  BENCH_REMAT=1 python -u tools/bench_bert.py

echo "=== session done; JSON lines: ==="
grep -h '"metric"' "$OUT"/hbm.log "$OUT"/bench_*.log "$OUT"/bert*.log \
  "$OUT"/gpt*.log 2>/dev/null
echo "logs in $OUT"

# Preserve the evidence in-tree immediately (VERDICT r2 item 1: mid-round
# artifacts, not end-of-round luck) — the session or relay may not
# survive to a second chance. Committing is done by the operator/driver.
ART="artifacts/onchip_r3"  # script already cd'd to the repo root
mkdir -p "$ART"
cp "$OUT"/*.log "$ART"/ 2>/dev/null
grep -h '"metric"' "$OUT"/bench_fused.log 2>/dev/null | tail -1 \
  > "$ART"/BENCH_LATEST.json || true
echo "artifacts copied to $ART"
