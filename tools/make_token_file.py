#!/usr/bin/env python
"""Tokenize raw text into the flat .npy token file the text datasets read
(`--data.dataset=tokens:<path.npy>` for causal LM, `tokens_mlm:<path.npy>`
for BERT MLM pretraining — data/text.py TokenFileLM/TokenFileMLM).

The reference's BERT consumed TFRecords produced by an offline
create_pretraining_data step (SURVEY.md §2a input-pipeline row); this is
that step for this framework, kept zero-dependency/zero-egress:

  wordpiece  greedy longest-match-first WordPiece over a LOCAL vocab.txt
             (the standard BERT vocab format, one token per line, ##
             continuation prefix) — byte-identical to the reference's
             tokenizer on the same vocab for whitespace-clean ASCII;
             basic-tokenization (lowercase, punctuation split) included.
  bytes      UTF-8 bytes + specials (vocab 256+5) — no vocab file needed;
             pair with --model.vocab_size=261.

Usage:
  python tools/make_token_file.py OUT.npy FILE [FILE...] \
      [--tokenizer=wordpiece --vocab=vocab.txt | --tokenizer=bytes]
"""

from __future__ import annotations

import argparse
import sys
import unicodedata

import numpy as np

# byte tokenizer specials (above the 256 byte values)
BYTE_PAD, BYTE_UNK, BYTE_CLS, BYTE_SEP, BYTE_MASK = 256, 257, 258, 259, 260
BYTE_VOCAB = 261


def _basic_tokens(text: str, lowercase: bool = True):
    """BERT BasicTokenizer: whitespace-clean, lowercase+strip accents,
    split punctuation into standalone tokens."""
    if lowercase:
        text = text.lower()
        text = unicodedata.normalize("NFD", text)
        text = "".join(c for c in text if unicodedata.category(c) != "Mn")
    out, word = [], []
    for ch in text:
        if ch.isspace():
            if word:
                out.append("".join(word))
                word = []
        elif (unicodedata.category(ch).startswith("P")
              or (33 <= ord(ch) <= 47) or (58 <= ord(ch) <= 64)
              or (91 <= ord(ch) <= 96) or (123 <= ord(ch) <= 126)):
            if word:
                out.append("".join(word))
                word = []
            out.append(ch)
        else:
            word.append(ch)
    if word:
        out.append("".join(word))
    return out


class WordPiece:
    def __init__(self, vocab_path: str, lowercase: bool = True):
        with open(vocab_path, encoding="utf-8") as f:
            self.vocab = {line.rstrip("\n"): i for i, line in enumerate(f)}
        if not self.vocab:
            raise SystemExit(f"empty vocab file: {vocab_path}")
        if "[UNK]" not in self.vocab:
            raise SystemExit(
                f"{vocab_path} has no [UNK] entry — unknown words would "
                "silently map to id 0; fix the vocab file")
        self.unk = self.vocab["[UNK]"]
        self.lowercase = lowercase

    def encode(self, text: str) -> list[int]:
        ids = []
        for word in _basic_tokens(text, self.lowercase):
            if word in self.vocab:
                ids.append(self.vocab[word])
                continue
            # greedy longest-match-first with ## continuations
            start, pieces, bad = 0, [], False
            while start < len(word):
                end = len(word)
                cur = None
                while start < end:
                    sub = word[start:end]
                    if start > 0:
                        sub = "##" + sub
                    if sub in self.vocab:
                        cur = self.vocab[sub]
                        break
                    end -= 1
                if cur is None:
                    bad = True
                    break
                pieces.append(cur)
                start = end
            ids.extend([self.unk] if bad else pieces)
        return ids


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("out")
    ap.add_argument("files", nargs="+")
    ap.add_argument("--tokenizer", choices=("wordpiece", "bytes"),
                    default="bytes")
    ap.add_argument("--vocab", default=None,
                    help="vocab.txt for --tokenizer=wordpiece")
    ap.add_argument("--no-lowercase", action="store_true")
    args = ap.parse_args()

    if args.tokenizer == "wordpiece":
        if not args.vocab:
            raise SystemExit("--tokenizer=wordpiece requires --vocab")
        enc = WordPiece(args.vocab, lowercase=not args.no_lowercase)
        encode = enc.encode
        vocab_size = len(enc.vocab)
        mask_hint = enc.vocab.get("[MASK]", "<set manually>")
    else:
        def encode(text: str) -> np.ndarray:
            # frombuffer, not a Python int list: one object per byte
            # would cost ~30-60x the corpus size in RAM on big files
            return np.frombuffer(
                text.encode("utf-8"), np.uint8).astype(np.int32)
        vocab_size = BYTE_VOCAB
        mask_hint = BYTE_MASK

    all_ids: list[np.ndarray] = []
    total = 0
    for path in args.files:
        with open(path, encoding="utf-8", errors="replace") as f:
            ids = encode(f.read())
        all_ids.append(np.asarray(ids, np.int32))
        total += len(ids)
        print(f"{path}: {len(ids)} tokens", file=sys.stderr)
    tokens = np.concatenate(all_ids) if all_ids else np.empty(0, np.int32)
    # np.save silently appends ".npy" to extension-less paths; normalize
    # up front so the printed train flags below name the real file
    if not args.out.endswith(".npy"):
        args.out += ".npy"
    np.save(args.out, tokens)
    print(f"wrote {args.out}: {total} tokens, tokenizer={args.tokenizer}, "
          f"vocab_size={vocab_size}")
    print("train (BERT MLM): --data.dataset=tokens_mlm:" + args.out
          + f" --data.vocab_size={vocab_size} --model.vocab_size="
          f"{vocab_size} --data.mask_token={mask_hint}", file=sys.stderr)
    print("train (causal LM): --data.dataset=tokens:" + args.out
          + f" --data.vocab_size={vocab_size} --model.vocab_size="
          f"{vocab_size}", file=sys.stderr)


if __name__ == "__main__":
    main()
