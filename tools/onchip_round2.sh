#!/bin/bash
# HISTORICAL (round-2 record; superseded by tools/onchip_round5.sh).
# Kept for the round's provenance: its JSON rows predate the
# obs/scaling.py provenance stamp, so platform context lives only in
# the logs. New measurement sessions: tools/onchip_round5.sh; scaling
# curves: tools/sweep.py (provenance-stamped dtf-scaling-1 reports).
# Round-2 on-chip measurement session (PERF_NOTES.md staged plan).
# Runs each step SEQUENTIALLY — never two TPU processes at once (single
# device lease behind the relay; a killed holder can wedge it).
# Usage: bash tools/onchip_round2.sh [outdir]   (default /tmp/onchip_r2)
# Each step logs to <outdir>/<step>.log; the script continues past
# failures so one bad step can't cost the rest of the session.
set -u
OUT=${1:-/tmp/onchip_r2}
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

run() { # name timeout_s cmd...
  local name=$1 t=$2; shift 2
  echo "=== $name ($(date -u +%H:%M:%S)) ==="
  timeout --signal=TERM --kill-after=60 "$t" "$@" \
    >"$OUT/$name.log" 2>&1
  local rc=$?
  echo "    rc=$rc  tail:"
  tail -3 "$OUT/$name.log" | sed 's/^/    /'
  return $rc
}

# 0. cheap probe — bail early if the relay is down
run probe 180 python -u -c "
import jax, jax.numpy as jnp
print(jax.devices(), float(jax.jit(lambda a:(a@a).sum())(jnp.ones((256,256),jnp.bfloat16))))
" || { echo 'relay down; aborting session'; exit 1; }

# 1. parity gate for every fused kernel (26 checks)
run validate 900 python -u tools/validate_fused_tpu.py

# 2. flagship bench: fused default (auto-falls-back) then standard
run bench_fused 1200 python -u bench.py
run bench_standard 1200 env BENCH_BLOCK_IMPL=standard python -u bench.py

# 3. kernel microbench at bench shapes (fwd then grad)
run microbench_fwd 900 python -u tools/bench_fused_kernels.py fwd 10
run microbench_grad 900 python -u tools/bench_fused_kernels.py grad 10

# 4. BERT-base MLM + GPT fused-LN ablation
run bert 1200 python -u tools/bench_bert.py
run bert_dense_attn 1200 env BENCH_ATTN=dense python -u tools/bench_bert.py
run gpt_plain 1200 env BENCH_MODEL=gpt python -u tools/bench_bert.py
run gpt_fused_ln 1200 env BENCH_MODEL=gpt BENCH_FUSED_LN=1 \
  python -u tools/bench_bert.py

echo "=== session done; JSON lines: ==="
grep -h '"metric"' "$OUT"/bench_*.log "$OUT"/bert*.log "$OUT"/gpt*.log \
  2>/dev/null
echo "logs in $OUT"
