#!/bin/bash
# HISTORICAL (round-4 record; superseded by tools/onchip_round5.sh —
# the tiered restructure of this queue. New sessions go there; scaling
# curves through tools/sweep.py, whose reports are provenance-stamped).
# Round-4 on-chip session — supersedes onchip_round3b.sh (same core queue,
# VERDICT r3 item 1) plus the round-4 additions:
#   - wide_deep embedding-tier row (VERDICT r3 item 5 — last family with
#     zero hardware evidence)
#   - jpeg-fed + BENCH_PUT_SYNC A/B inside the same session (item 3)
#   - 4k flash block-size sweep point (item 4 / §5.7)
# Runs under tools/chip_session.sh (the watcher wraps it), so every other
# framework-importing python on the host pins itself to CPU for the
# duration (utils/chip_lock.py — the round-3 lease collision, mechanized).
# Usage: bash tools/onchip_round4.sh [outdir]   (default /tmp/onchip_r4)
set -u
cd "$(dirname "$0")/.."
OUT=$(readlink -f "${1:-/tmp/onchip_r4}")
mkdir -p "$OUT"

ART="artifacts/onchip_r4"
mkdir -p "$ART"

run() { # name timeout_s cmd...
  local name=$1 t=$2; shift 2
  echo "=== $name ($(date -u +%H:%M:%S)) ==="
  timeout --signal=TERM --kill-after=60 "$t" "$@" \
    >"$OUT/$name.log" 2>&1
  local rc=$?
  echo "    rc=$rc  tail:"
  tail -3 "$OUT/$name.log" | sed 's/^/    /'
  # preserve in-tree IMMEDIATELY: the relay has died mid-session twice;
  # only committed files survive a round end
  cp "$OUT/$name.log" "$ART/${name}.log" 2>/dev/null
  return $rc
}

run probe 180 python -u -c "
import jax, jax.numpy as jnp
print(jax.devices(), float(jax.jit(lambda a:(a@a).sum())(jnp.ones((256,256),jnp.bfloat16))))
" || { echo 'relay down; aborting session'; exit 1; }

# Ordered by value-per-minute (windows have died at 41 min and 75 min):
# roofline + headline first, then the never-measured tiers, then A/Bs.

# 1. corrected roofline: RTT-subtracted HBM/MXU + host->device bandwidth
#    — decides whether 0.50 MFU is chip-bound or program-bound here
run hbm 900 env HBM_ITERS=64 python -u tools/bench_hbm.py

# 2. flagship bench — unpinned: A/Bs fused-vs-standard, reports the faster
run bench_auto 1800 python -u bench.py
LATEST=$(grep -h '"metric"' "$OUT"/bench_auto.log 2>/dev/null | tail -1)
[ -n "$LATEST" ] && printf '%s\n' "$LATEST" > "$ART"/BENCH_LATEST.json

# 3. first-ever transformer numbers (MXU-bound tier; lost to the r3 lease
#    collision) — plain first so the suite's headline lands even if the
#    window dies here
run bert 1200 python -u tools/bench_bert.py
run gpt_plain 1200 env BENCH_MODEL=gpt python -u tools/bench_bert.py
run gpt_long4k 1500 env BENCH_MODEL=gpt BENCH_SEQ=4096 BENCH_BATCH=4 \
  BENCH_REMAT=1 python -u tools/bench_bert.py

# 4. first-ever embedding-tier number (VERDICT r3 item 5)
run wide_deep 1200 python -u tools/bench_wide_deep.py

# 5. fed-window proof (VERDICT r3 item 3): jpeg-decode-fed and the
#    PUT_SYNC A/B in the same session; bench_hbm above already reported
#    host_to_device_gbps, making these rows self-explaining
run bench_jpeg 1500 env BENCH_DATA=jpeg python -u bench.py
run bench_jpeg_putsync 1500 env BENCH_DATA=jpeg BENCH_PUT_SYNC=1 python -u bench.py

# 6. validator incl. the bench-shape compile/execute sweep
run validate 1500 python -u tools/validate_fused_tpu.py

# 7. pinned A/B rows (kernel-tier verdict: does fused-fwd/XLA-bwd beat
#    standard end-to-end?)
run bench_fused_xlabwd 1200 env BENCH_BLOCK_IMPL=fused python -u bench.py
run bench_fused_pallasbwd 1200 env BENCH_BLOCK_IMPL=fused \
  DTF_FUSED_BWD=pallas python -u bench.py
run bench_standard 1200 env BENCH_BLOCK_IMPL=standard python -u bench.py

# 8. transformer ablations + flash block sweep (512 and 4k tiles)
run bert_wide_flash 1200 env DTF_FLASH_BLOCK_Q=256 DTF_FLASH_BLOCK_K=512 \
  python -u tools/bench_bert.py
run bert_dense_attn 1200 env BENCH_ATTN=dense python -u tools/bench_bert.py
run gpt_fused_ln 1200 env BENCH_MODEL=gpt BENCH_FUSED_LN=1 \
  python -u tools/bench_bert.py
run gpt_long4k_k512 1500 env BENCH_MODEL=gpt BENCH_SEQ=4096 BENCH_BATCH=4 \
  BENCH_REMAT=1 DTF_FLASH_BLOCK_Q=128 DTF_FLASH_BLOCK_K=512 \
  python -u tools/bench_bert.py
# GPT batch knee: does 64/chip fit past the [B,S,vocab] logits tier?
run gpt_b64 1200 env BENCH_MODEL=gpt BENCH_BATCH=64 BENCH_REMAT=1 \
  python -u tools/bench_bert.py
# chunked-xent A/B: the dense [B,S,vocab] loss at the same batch
# (expected to lose on memory pressure or OOM — that IS the datum)
run gpt_dense_xent 1200 env BENCH_MODEL=gpt BENCH_XENT_CHUNK=0 \
  python -u tools/bench_bert.py
# bf16 vocab-head A/B: the ~25-30%-of-FLOPs head on the fast MXU tier
run gpt_head_bf16 1200 env BENCH_MODEL=gpt BENCH_HEAD_DTYPE=bfloat16 \
  python -u tools/bench_bert.py
run bert_remat 1200 env BENCH_REMAT=1 python -u tools/bench_bert.py
run bert_fused_qkv 1200 env BENCH_FUSED_QKV=1 python -u tools/bench_bert.py
# batch knee probe: does 256/chip beat 128 (HBM pressure vs MXU feed)?
run bert_b256 1200 env BENCH_BATCH=256 BENCH_REMAT=1 python -u tools/bench_bert.py

# 8b. per-shape kernel microbenches: fwd (pallas won 1.0-2.5x in r3,
#     re-confirm) and grad with the NEW single-pass backward (r3 only
#     measured the two-pass). grad is stall-prone (r3 s3_conv1 rc=124;
#     that shape runs last and the step timeout contains it).
run microbench_fwd 900 python -u tools/bench_fused_kernels.py fwd
run microbench_grad 900 env DTF_FUSED_BWD=pallas \
  python -u tools/bench_fused_kernels.py grad

# 9. profile capture at bench config (fused fwd + XLA bwd)
rm -rf "$OUT/profile"
run profile 1200 python -u examples/train.py resnet50_imagenet \
  --train.num_steps=30 --train.profile=true \
  --train.profile_dir="$OUT/profile" \
  --model.norm_dtype=bfloat16 --model.stem=space_to_depth \
  --model.block_impl=fused --data.global_batch_size=256 \
  --data.image_size=224 --checkpoint.directory= \
  --train.log_every=10
tar -C "$OUT" -czf "$OUT/profile.tgz" profile 2>/dev/null \
  && echo "    profile.tgz $(du -h "$OUT/profile.tgz" | cut -f1)"

# 10. LAST (can stall — r3 microbench_grad rc=124): AOT-compile the
#     non-default Pallas backward at every bench shape
run validate_pallas_bwd 1200 env VALIDATE_PALLAS_BWD=only \
  python -u tools/validate_fused_tpu.py

echo "=== session done; JSON lines: ==="
grep -h '"metric"' "$OUT"/*.log 2>/dev/null
echo "logs in $OUT"

# per-step logs + BENCH_LATEST.json were preserved in-tree by run()
# already; only the profile tarball is new work here
cp "$OUT/profile.tgz" "$ART/profile_r4.tgz" 2>/dev/null || true
echo "artifacts in $ART"
