#!/usr/bin/env python
"""Embedding-tier benchmark: Wide&Deep CTR training throughput on the
available chip(s) — the BASELINE.json:11 workload family, same honest
timing contract as bench.py / bench_bert.py (value-fetch sync, steady-
state window after warmup).

Recommender steps are gather/scatter- and bandwidth-dominated, not
MXU-dominated: alongside examples/sec the row reports the analytic
embedding bytes moved per example and the implied achieved HBM rate, the
roofline that actually binds this family. With a model axis (virtual
mesh or multi-chip), the vocab-sharded tables exercise the all_to_all /
collective lookup path (ops/embedding.py).

Prints ONE JSON line to stdout; diagnostics to stderr.

Env knobs:
  BENCH_BATCH        PER-CHIP batch (default 16384 on TPU, 256 on CPU)
  BENCH_STEPS        measured steps (default 20)
  BENCH_WD_VOCAB     per-feature vocab size (default 100000 TPU, 1024 CPU)
  BENCH_WD_FEATURES  number of categorical features (default 26, Criteo)
  BENCH_WD_EMBED     embedding dim (default 64 TPU, 8 CPU)
  BENCH_MESH_MODEL   model-axis size for embedding parallelism (default 1;
                     data axis takes the rest of the devices)
  BENCH_EMBED_IMPL   "take" (GSPMD lookup, default) | "explicit"
                     (range-sharded shard_map lookup)
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main() -> None:
    import jax

    from distributed_tensorflow_tpu.utils import benchmarking as bm

    bm.fall_back_to_cpu_if_unreachable(log=log)
    bm.honor_env_platform()
    import numpy as np

    from distributed_tensorflow_tpu.models import wide_deep as wd
    from distributed_tensorflow_tpu.parallel import MeshSpec, build_mesh, describe
    from distributed_tensorflow_tpu.parallel import sharding as sh
    from distributed_tensorflow_tpu.train import (
        StepOptions, init_train_state, jit_train_step, make_train_step,
    )
    from distributed_tensorflow_tpu.utils import flops as flops_lib
    from distributed_tensorflow_tpu.workloads.wide_deep import _canonical_tx
    from distributed_tensorflow_tpu.workloads.runner import RunConfig
    from distributed_tensorflow_tpu.train import OptimizerConfig

    devices, n_chips, platform, on_tpu = bm.describe_devices()
    log(f"bench devices: {devices} (platform={platform})")

    n_feat = int(os.environ.get("BENCH_WD_FEATURES", "26"))
    vocab = int(os.environ.get("BENCH_WD_VOCAB",
                               "100000" if on_tpu else "1024"))
    embed = int(os.environ.get("BENCH_WD_EMBED", "64" if on_tpu else "8"))
    per_chip_batch = int(os.environ.get(
        "BENCH_BATCH", "16384" if on_tpu else "256"))
    model_axis = int(os.environ.get("BENCH_MESH_MODEL", "1"))
    embed_impl = os.environ.get("BENCH_EMBED_IMPL", "take")
    global_batch = per_chip_batch * n_chips

    cfg = wd.WideDeepConfig(
        vocab_sizes=(vocab,) * n_feat,
        embed_dim=embed,
        dense_features=13,
        hidden_sizes=(1024, 512, 256) if on_tpu else (64, 32),
        embed_impl=embed_impl,
    )
    mesh = build_mesh(MeshSpec(data=-1, model=model_axis))
    log(f"mesh: {describe(mesh)}  tables={n_feat}x{vocab}x{embed} "
        f"embed_impl={embed_impl} global_batch={global_batch}")

    model = wd.WideDeep(cfg, mesh)
    # canonical FTRL-wide / AdaGrad-deep split, same as the workload
    run_cfg = RunConfig(model=cfg, optimizer=OptimizerConfig(
        name="auto", learning_rate=0.05))
    tx = _canonical_tx(run_cfg)
    assert tx is not None
    state, specs = init_train_state(
        wd.make_init_fn(cfg, mesh), tx, mesh, jax.random.PRNGKey(0),
        param_rules=wd.WIDE_DEEP_RULES,
    )
    step = jit_train_step(
        make_train_step(wd.ctr_loss_fn(model), tx, StepOptions()),
        mesh, specs,
    )

    rng = np.random.RandomState(0)
    from jax.sharding import NamedSharding

    batch_np = {
        "cat": rng.randint(0, vocab, (global_batch, n_feat)).astype(np.int32),
        "dense": rng.randn(global_batch, 13).astype(np.float32),
        "label": (rng.rand(global_batch) > 0.5).astype(np.float32),
    }
    batch = jax.tree.map(
        lambda x: jax.device_put(
            x, NamedSharding(mesh, sh.batch_spec(np.ndim(x)))
        ),
        batch_np,
    )

    measured = int(os.environ.get("BENCH_STEPS", "20"))
    state, steps_per_sec, final_loss = bm.timed_steps(
        step, state, lambda: batch, warmup=3, measured=measured, log=log,
    )
    examples_per_sec_per_chip = steps_per_sec * global_batch / n_chips

    # Embedding-traffic roofline context (analytic, f32 tables): fwd
    # gather read + bwd scatter-add read-modify-write of the same rows
    # (3x total) for deep tables + the 1-wide columns, both per feature.
    bytes_per_example = 3 * n_feat * (embed + 1) * 4
    embed_gbps = examples_per_sec_per_chip * bytes_per_example / 1e9
    # shared MFU helper (obs/goodput.py): applies the fwd+bwd multiplier
    from distributed_tensorflow_tpu.obs import goodput

    peak = flops_lib.peak_flops_per_chip(devices[0])
    mfu = goodput.train_mfu(
        wd.flops_per_example(cfg) * global_batch, steps_per_sec,
        n_chips=n_chips, peak_per_chip=peak,
    )
    log(f"steps/sec={steps_per_sec:.3f} "
        f"examples/sec/chip={examples_per_sec_per_chip:.0f} "
        f"embed-traffic={embed_gbps:.1f} GB/s MFU={mfu:.4f}")

    # vs_baseline for THIS family is achieved-vs-spec HBM bandwidth, not
    # MFU/0.50: the module docstring's own roofline argument — comparing
    # a gather/scatter-bound workload's MFU to the ResNet MXU target is
    # a misleading datum (ADVICE r4). 819 GB/s = v5e HBM spec
    # (tools/bench_hbm.py); on the CPU fallback the spec doesn't apply
    # and the field reports 0.0 (full_size_model already flags the row).
    print(json.dumps({
        "metric": "wide_deep_examples_per_sec_per_chip",
        "value": round(examples_per_sec_per_chip, 1),
        "unit": "examples/sec/chip",
        "vs_baseline": round(embed_gbps / 819.0, 4) if on_tpu else 0.0,
        "vs_baseline_basis": "embed_traffic_gbps / 819 GB/s v5e HBM spec",
        "mfu": round(mfu, 4),
        "platform": platform,
        "n_chips": n_chips,
        "global_batch": global_batch,
        "tables": n_feat,
        "vocab_size": vocab,
        "embed_dim": embed,
        "embed_impl": embed_impl,
        "mesh_model_axis": model_axis,
        "embed_bytes_per_example": bytes_per_example,
        "embed_traffic_gbps": round(embed_gbps, 2),
        "full_size_model": bool(on_tpu),
    }))


if __name__ == "__main__":
    main()
