#!/usr/bin/env python
"""Real-text convergence demo for the transformer family, end to end —
REAL English prose (this repo's own *.md documentation, the only genuine
text corpus in a zero-egress image) -> tools/make_token_file.py byte
tokenizer -> the token-file streams -> training -> standalone eval
restore -> held-out accuracy. Two objectives share the harness:

  --objective=mlm (default)  bert_pretrain over `tokens_mlm:`
      (TokenFileMLM 80/10/10 corruption, gathered positions); gate on
      held-out masked-byte accuracy.
  --objective=lm             gpt_lm over `tokens:` (TokenFileLM causal
      windows); gate on held-out next-byte accuracy.

Character-level MLM with bidirectional context is genuinely learnable
(English orthography), so the gate is meaningful: unigram guessing
tops out ~13% ('e'/space), while a trained model recovers masked bytes
from both-side context far above that. A broken tokenizer, masking
stream, gathered-head path, or checkpoint restore all drop the score
back toward the unigram floor.

Usage: python tools/convergence_demo_mlm.py [--steps 400] [--min-acc 0.35]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from distributed_tensorflow_tpu.utils.benchmarking import (  # noqa: E402
    fall_back_to_cpu_if_unreachable, honor_env_platform,
)

honor_env_platform()
fall_back_to_cpu_if_unreachable(log=lambda m: print(m, file=sys.stderr))

VOCAB, MASK = 261, 260  # byte tokenizer: 256 bytes + 5 specials
# --long configuration, defined ONCE (CLI args + artifact stamp share it)
LONG_MESH_SEQ, LONG_SEQ_IMPL = 4, "ring"


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=1600)
    ap.add_argument("--objective", choices=("mlm", "lm"), default=None)
    ap.add_argument("--min-acc", type=float, default=None,
                    help="held-out accuracy gate (unigram floor ~0.13); "
                         "default 0.35, or 0.25 for --long (seq-256 ring "
                         "training converges slower per step — 0.303 "
                         "measured at 3600 steps, artifacts/"
                         "lm_long_ring_r4.json)")
    ap.add_argument("--long", action="store_true",
                    help="long-context SP variant: causal LM at seq 256 "
                         "trained THROUGH ring attention on a seq=4 mesh "
                         "(needs a device count divisible by 4, e.g. "
                         "XLA_FLAGS=--xla_force_host_platform_device_"
                         "count=8) — the SURVEY §5.7 strategy learning "
                         "on real text end to end, not just passing "
                         "parity tests")
    args = ap.parse_args()
    if args.long and args.objective == "mlm":
        ap.error("--long is a causal-LM variant; drop --objective=mlm")
    if args.objective is None:
        args.objective = "lm" if args.long else "mlm"
    if args.min_acc is None:
        args.min_acc = 0.25 if args.long else 0.35

    from distributed_tensorflow_tpu import workloads

    work = tempfile.mkdtemp(prefix="dtf_mlm_demo_")

    # real prose: every markdown file in the repo (≈100 KB of English),
    # split held-out by FILE so eval text was never seen in training
    mds = sorted(
        glob.glob(os.path.join(REPO, "*.md"))
        + glob.glob(os.path.join(REPO, "docs", "*.md"))
    )
    if len(mds) < 4:
        raise SystemExit(f"need >= 4 .md files, found {len(mds)}")
    eval_files, train_files = mds[::4], [m for m in mds if m not in mds[::4]]

    for out, files in (("train.npy", train_files), ("eval.npy", eval_files)):
        subprocess.run(
            [sys.executable, os.path.join(REPO, "tools/make_token_file.py"),
             os.path.join(work, out), *files],
            check=True, capture_output=True,
        )

    mlm = args.objective == "mlm"
    workload = "bert_pretrain" if mlm else "gpt_lm"
    prefix = "tokens_mlm" if mlm else "tokens"
    seq = 256 if args.long else 64
    common = [
        f"--data.vocab_size={VOCAB}",
        f"--data.seq_len={seq}",
        f"--data.global_batch_size={16 if args.long else 64}",
        *(
            [f"--data.mask_token={MASK}", "--data.max_predictions=10"]
            if mlm else []
        ),
        f"--model.vocab_size={VOCAB}",
        "--model.num_layers=3",
        "--model.d_model=128",
        "--model.num_heads=4",
        "--model.d_ff=256",
        f"--model.max_len={seq}",
        "--mesh.model=1",
        *(
            # ring attention over a real seq axis + remat, the long-
            # context preset's exact configuration at demo scale; data=-1
            # absorbs whatever device count the rig has beyond seq=4
            [f"--mesh.seq={LONG_MESH_SEQ}", "--mesh.data=-1",
             f"--model.seq_impl={LONG_SEQ_IMPL}", "--model.remat=true"]
            if args.long else ["--mesh.data=-1"]
        ),
    ]
    ckdir = os.path.join(work, "ck")
    result = workloads.run_workload(workload, [
        f"--data.dataset={prefix}:{work}/train.npy",
        f"--train.num_steps={args.steps}",
        f"--train.log_every={min(50, args.steps)}",
        "--train.eval_batches=0",
        f"--checkpoint.directory={ckdir}",
        "--checkpoint.async_save=false",
        "--checkpoint.save_on_preemption=false",
        "--optimizer.learning_rate=0.003",
        *common,
    ])

    eval_metrics = workloads.eval_workload(workload, [
        f"--data.dataset={prefix}:{work}/eval.npy",
        f"--checkpoint.directory={ckdir}",
        "--train.eval_batches=5",
        *common,
    ])
    acc = float(eval_metrics.get("accuracy", 0.0))
    print(json.dumps({
        "objective": "lm_long_ring" if args.long else args.objective,
        "train_loss": round(float(result.history[-1]["loss"]), 4),
        "eval_masked_acc" if mlm else "eval_next_byte_acc": round(acc, 4),
        "steps": args.steps,
        **({"seq_len": seq, "mesh_seq": LONG_MESH_SEQ,
            "seq_impl": LONG_SEQ_IMPL, "remat": True}
           if args.long else {}),
        "dataset": f"repo .md prose, byte-tokenized; "
                   f"{len(train_files)} train / {len(eval_files)} "
                   f"held-out files",
    }))
    if acc < args.min_acc:
        raise SystemExit(
            f"held-out accuracy {acc:.3f} < {args.min_acc} gate")


if __name__ == "__main__":
    main()
