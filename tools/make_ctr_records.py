#!/usr/bin/env python
"""Convert Criteo-format CTR TSV into the fixed-size CTR record file the
Wide&Deep workload reads (`--data.dataset=ctr:<path>`, data/recsys.py
CTRRecordDataset over the native record loader).

Input format (the Criteo display-advertising layout the reference's
Wide&Deep consumed): one example per line,
``label \\t I1..In_dense \\t C1..Cn_cat`` — integer dense features and
hex-string categorical features, empty fields = missing. Field counts
are inferred from the first line (Criteo: 13 dense, 26 categorical).

Transforms (the standard recipe):
- dense: ``log1p(max(v, 0))`` f32, missing -> 0
- categorical: SplitMix64 hash of the raw token, modulo ``--vocab-size``
  (missing -> id 0). Stable across runs/hosts — no Python hash().

Writes ``OUT`` (records) + ``OUT.meta.json`` (field counts, vocab sizes,
row count) and prints the exact training flags.

Usage:
  python tools/make_ctr_records.py OUT train.txt [more.txt...] \\
      [--vocab-size 100003] [--limit N]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_M64 = (1 << 64) - 1


def _splitmix64(x: int) -> int:
    x = (x + 0x9E3779B97F4A7C15) & _M64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _M64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _M64
    return (x ^ (x >> 31)) & _M64


def hash_token(tok: str, vocab: int) -> int:
    """Stable categorical hash: bytes -> u64 chain -> mod vocab.
    Reserved: missing -> 0, so real tokens land in [1, vocab)."""
    h = 0x243F6A8885A308D3
    for b in tok.encode("utf-8"):
        h = _splitmix64(h ^ b)
    return 1 + h % (vocab - 1)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("out")
    ap.add_argument("files", nargs="+")
    ap.add_argument("--vocab-size", type=int, default=100003,
                    help="hash-mod vocab per categorical field")
    ap.add_argument("--n-dense", type=int, default=None,
                    help="dense field count (default: min(13, n_fields-1) "
                         "— the Criteo layout); set explicitly for other "
                         "splits")
    ap.add_argument("--limit", type=int, default=None,
                    help="stop after N examples")
    args = ap.parse_args()

    from distributed_tensorflow_tpu.data.recsys import ctr_record_dtype

    n_dense = args.n_dense
    n_cat = None
    dt = None
    total = 0
    # token -> hashed id cache: Criteo categorical tokens repeat heavily,
    # so this collapses the per-byte Python hashing to one pass per
    # UNIQUE token (the difference between hours and minutes at scale)
    tok_cache: dict[str, int] = {}

    def hash_cached(tok: str) -> int:
        h = tok_cache.get(tok)
        if h is None:
            h = tok_cache[tok] = hash_token(tok, args.vocab_size)
        return h

    def flush(chunk: list[list[str]], out) -> None:
        nonlocal total
        if not chunk:
            return
        arr = np.zeros(len(chunk), dt)
        arr["label"] = [float(p[0] or 0) for p in chunk]
        dense = np.zeros((len(chunk), n_dense), np.float64)
        for r, parts in enumerate(chunk):
            for i, v in enumerate(parts[1 : 1 + n_dense]):
                if v:
                    try:
                        dense[r, i] = max(float(v), 0.0)
                    except ValueError:
                        raise SystemExit(
                            f"non-numeric dense field {v!r} at column "
                            f"{1 + i} — is --n-dense={n_dense} right for "
                            "this file?") from None
            arr["cat"][r] = [hash_cached(v) if v else 0
                             for v in parts[1 + n_dense :]]
        arr["dense"] = np.log1p(dense)
        arr.tofile(out)
        total += len(chunk)

    chunk: list[list[str]] = []
    with open(args.out, "wb") as out:
        for path in args.files:
            with open(path, encoding="utf-8", errors="replace") as f:
                for line in f:
                    parts = line.rstrip("\n").split("\t")
                    if n_cat is None:
                        # infer the layout from the first line: Criteo is
                        # 1 label + 13 dense + 26 categorical
                        n_total = len(parts) - 1
                        if n_dense is None:
                            n_dense = min(13, n_total)
                        n_cat = n_total - n_dense
                        if n_cat <= 0:
                            raise SystemExit(
                                f"{path}: need >= 1 categorical field "
                                f"after {n_dense} dense; line has "
                                f"{n_total} features (--n-dense wrong?)")
                        dt = ctr_record_dtype(n_dense, n_cat)
                    if len(parts) != 1 + n_dense + n_cat:
                        continue  # malformed line
                    chunk.append(parts)
                    if len(chunk) >= 65536:
                        flush(chunk, out)
                        chunk = []
                    if args.limit and total + len(chunk) >= args.limit:
                        break
            flush(chunk, out)
            chunk = []
            print(f"{path}: {total} examples so far", file=sys.stderr)
            if args.limit and total >= args.limit:
                break
    if total == 0:
        raise SystemExit("no examples converted")

    meta = {
        "n_records": total,
        "dense_features": n_dense,
        "vocab_sizes": [args.vocab_size] * n_cat,
        "record_bytes": dt.itemsize,
    }
    with open(args.out + ".meta.json", "w") as f:
        json.dump(meta, f, indent=1)
    print(f"wrote {args.out}: {total} records "
          f"({n_dense} dense, {n_cat} categorical, "
          f"{dt.itemsize} B/record)")
    vs = ",".join(str(args.vocab_size) for _ in range(n_cat))
    print(f"train: python examples/train.py wide_deep "
          f"--data.dataset=ctr:{args.out} "
          f"--model.dense_features={n_dense} "
          f"--model.vocab_sizes=[{vs}]", file=sys.stderr)


if __name__ == "__main__":
    main()
