#!/usr/bin/env python
"""Microbench: fused Pallas conv1x1+BN kernels vs the unfused XLA sequence
at ResNet-50 training shapes (PERF_NOTES.md follow-up). Run on the real
chip: `python tools/bench_fused_kernels.py [fwd|grad] [reps]`.

Timing: the whole rep-loop lives in one jit (lax.fori_loop) with a scalar
carry that every iteration's outputs fold into, and the carry is fetched
— the only execution-forcing pattern that works through the tunnel
(bench.py:122-126).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from distributed_tensorflow_tpu.ops.fused_conv_bn import (
    bn_scale_shift, conv1x1_bn_act, moments_from_sums,
)

# (name, M, cin, cout, prologue) — b=256 ResNet-50 bottleneck 1x1s
SHAPES = [
    ("s0_conv3", 256 * 56 * 56, 64, 256, True),
    ("s1_conv1", 256 * 28 * 28, 512, 128, False),
    ("s1_conv3", 256 * 28 * 28, 128, 512, True),
    ("s2_conv3", 256 * 14 * 14, 256, 1024, True),
    ("s3_conv1", 256 * 7 * 7, 2048, 512, False),
]


def unfused(x, w, scale, shift, prologue):
    h = x
    if prologue:
        h = (x.astype(jnp.float32) * scale + shift)
        h = jnp.maximum(h, 0.0).astype(x.dtype)
    y = jnp.dot(h, w, preferred_element_type=jnp.float32).astype(x.dtype)
    st = y.astype(jnp.float32)
    return y, st.sum(0), (st * st).sum(0)


def fused(x, w, scale, shift, prologue):
    if prologue:
        return conv1x1_bn_act(x, w, scale, shift, relu=True, emit_stats=True)
    return conv1x1_bn_act(x, w, emit_stats=True)


def loss_of(fn, prologue):
    def loss(x, w, scale, shift):
        y, s, ssq = fn(x, w, scale, shift, prologue)
        mean, var = moments_from_sums(s, ssq, y.shape[0])
        sc2, sh2 = bn_scale_shift(mean, var, jnp.ones_like(mean),
                                  jnp.zeros_like(mean), 1e-5)
        # consume y the way the next layer would: one more normalize pass
        return (y.astype(jnp.float32) * sc2 + sh2).sum()

    return loss


def timed(fn, args, reps):
    def body(_, carry):
        out = fn(*args)
        leaves = jax.tree.leaves(out)
        return carry + sum(jnp.sum(l).astype(jnp.float32) * 0 for l in leaves) + 1

    run = jax.jit(lambda: jax.lax.fori_loop(0, reps, body, 0.0))
    float(jax.device_get(run()))  # compile + warm
    t0 = time.perf_counter()
    float(jax.device_get(run()))
    return (time.perf_counter() - t0) / reps * 1e3


def main():
    mode = sys.argv[1] if len(sys.argv) > 1 else "fwd"
    reps = int(sys.argv[2]) if len(sys.argv) > 2 else 20
    # this microbench exists to MEASURE the pallas path, including shapes
    # the landmine guard (_tiling.PALLAS_BWD_KNOWN_SLOW) would reroute
    import os

    os.environ["DTF_FUSED_BWD_FORCE"] = "1"
    r = np.random.RandomState(0)
    print(f"backend={jax.default_backend()} mode={mode} reps={reps}")
    print(f"{'shape':10s} {'M':>8s} {'cin':>5s} {'cout':>5s} "
          f"{'xla_ms':>8s} {'pallas_ms':>9s} {'speedup':>7s}")
    for name, M, cin, cout, prologue in SHAPES:
        x = jnp.asarray(r.randn(M, cin), jnp.bfloat16)
        w = jnp.asarray(r.randn(cin, cout) * 0.05, jnp.bfloat16)
        scale = jnp.asarray(r.rand(cin) + 0.5, jnp.float32)
        shift = jnp.asarray(r.randn(cin) * 0.1, jnp.float32)
        args = (x, w, scale, shift)
        if mode == "fwd":
            t_x = timed(lambda *a: unfused(*a, prologue), args, reps)
            t_p = timed(lambda *a: fused(*a, prologue), args, reps)
        else:
            gx = jax.grad(loss_of(unfused, prologue), argnums=(0, 1))
            gp = jax.grad(loss_of(fused, prologue), argnums=(0, 1))
            t_x = timed(gx, args, reps)
            t_p = timed(gp, args, reps)
        print(f"{name:10s} {M:8d} {cin:5d} {cout:5d} "
              f"{t_x:8.3f} {t_p:9.3f} {t_x / t_p:7.2f}x", flush=True)


if __name__ == "__main__":
    main()
