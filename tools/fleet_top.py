#!/usr/bin/env python
"""fleet_top — live (or one-shot) text view of a fleet's telemetry.

Reads the per-worker artifacts the fleet control plane already leaves
under a fleet dir — ``fleetsnap-<i>.json`` telemetry snapshots
(obs/fleetview.SnapshotExporter) and ``heartbeat-<i>.json`` liveness
records (resilience/fleet.HeartbeatWriter) — folds the snapshots
through the same ``FleetAggregator`` the ``FleetSupervisor`` runs, and
prints one row per worker plus the fleet-wide aggregates:

    worker  inc  seq  step  phase    hb.seq  stale_s  steps   goodput
    0       2    14   6     done     31      0.0      6       0.82
    1       2    12   6     done     29      0.0      6       0.79
    fleet: goodput_fraction=0.81 steps_total=12 step p50=3.1ms p99=4.8ms

The fleet aggregates come from MERGED per-worker registries (counters
and histogram buckets sum; the p99 is read from the union buckets) —
never from averaging per-worker readings, the aggregation soundness
rule docs/observability.md "Fleet observability" pins. Staleness is
judged on THIS process's clock from observed (pid, seq) changes, so on
``--once`` (a single observation) it reads 0.0 — the column becomes
meaningful in live mode, where a worker that stopped exporting ages
visibly while the others stay fresh.

Usage:
    python tools/fleet_top.py --fleet-dir <dir> --once
    python tools/fleet_top.py --fleet-dir <dir> --interval 2.0
"""

import argparse
import glob
import os
import re
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

_SNAP_RE = re.compile(r"fleetsnap-(\d+)\.json$")


def discover_workers(fleet_dir: str) -> list[int]:
    """Worker indices with a snapshot file under the fleet dir."""
    out = []
    for p in glob.glob(os.path.join(fleet_dir, "fleetsnap-*.json")):
        m = _SNAP_RE.search(os.path.basename(p))
        if m:
            out.append(int(m.group(1)))
    return sorted(out)


def _fmt(v, spec="{:.2f}"):
    return spec.format(v) if v is not None else "-"


def render_once(agg, fleet_dir: str, out=sys.stdout) -> None:
    from distributed_tensorflow_tpu.obs import fleetview as fv
    from distributed_tensorflow_tpu.obs import goodput
    from distributed_tensorflow_tpu.resilience import fleet as fl

    view = agg.poll()
    print(f"{'worker':<7} {'inc':<4} {'seq':<5} {'step':<6} {'phase':<10} "
          f"{'hb.seq':<7} {'stale_s':<8} {'steps':<7} {'goodput':<7}",
          file=out)
    for i in agg.workers:
        st = agg.status.get(i)
        if st is None:
            print(f"{i:<7} {'-':<4} {'-':<5} {'-':<6} {'-':<10} {'-':<7} "
                  f"{'-':<8} {'-':<7} {'-':<7}", file=out)
            continue
        hb = fl.read_heartbeat(fl.heartbeat_path(fleet_dir, i))
        stale = agg.registry.get(fv.FLEET_WORKER_STALENESS, worker=str(i))
        steps = view.get("train_steps_total", worker=str(i))
        frac = view.get(goodput.GOODPUT_FRACTION, worker=str(i))
        print(f"{i:<7} {st['incarnation']:<4} {st['seq']:<5} "
              f"{_fmt(st['step'], '{}'):<6} {str(st['phase']):<10} "
              f"{_fmt(hb.seq if hb else None, '{}'):<7} "
              f"{_fmt(stale.value if stale else None):<8} "
              f"{_fmt(steps.value if steps else None, '{:.0f}'):<7} "
              f"{_fmt(frac.value if frac else None):<7}", file=out)
    frac = view.get(fv.FLEET_GOODPUT_FRACTION)
    steps = view.get("train_steps_total")
    hist = view.get("train_step_seconds")
    parts = [f"goodput_fraction={_fmt(frac.value if frac else None)}",
             f"steps_total={_fmt(steps.value if steps else None, '{:.0f}')}"]
    if hist is not None and hist.count:
        parts.append(f"step p50={hist.percentile(0.5) * 1e3:.1f}ms "
                     f"p99={hist.percentile(0.99) * 1e3:.1f}ms")
    print("fleet: " + " ".join(parts), file=out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--fleet-dir", required=True,
                    help="fleet control dir (fleetsnap-*.json, "
                         "heartbeat-*.json)")
    ap.add_argument("--once", action="store_true",
                    help="print one view and exit (CI mode)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="live-mode refresh seconds")
    args = ap.parse_args(argv)

    from distributed_tensorflow_tpu.obs import fleetview as fv

    workers = discover_workers(args.fleet_dir)
    if not workers:
        print(f"fleet_top: no fleetsnap-*.json under {args.fleet_dir}",
              file=sys.stderr)
        return 2
    agg = fv.FleetAggregator(args.fleet_dir, workers)
    if args.once:
        render_once(agg, args.fleet_dir)
        return 0
    try:
        while True:
            render_once(agg, args.fleet_dir)
            print(flush=True)
            time.sleep(max(args.interval, 0.1))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    raise SystemExit(main())
