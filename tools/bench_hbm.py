#!/usr/bin/env python
"""HBM + MXU microbenchmark — the roofline inputs for PERF_NOTES.md.

Measures, on whatever backend is reachable:
  1. sustained streaming bandwidth: jit x+1 over a 1 GiB bf16 buffer
     (1 read + 1 write per element), fori_loop-chained so the tunnel
     cannot hide dispatch latency;
  2. read-reduce bandwidth: jit sum over the same buffer (1 read);
  3. bf16 matmul peak: 8192^3 chained matmuls vs the 197 TFLOP/s v5e spec.

Round-2 measured ~445 GB/s streaming (55% of the 819 GB/s v5e spec) on
the tunneled chip; the whole ResNet roofline argument leans on that one
number (VERDICT r2 Weak #2), so this tool exists to re-measure it on any
healthy chip and keep the method pinned in-tree.

Prints one JSON line per metric. Timing fetches a VALUE that
data-depends on every iteration (utils/benchmarking.py discipline —
block_until_ready returns before execution through the tunnel).
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from distributed_tensorflow_tpu.utils.benchmarking import (  # noqa: E402
    fall_back_to_cpu_if_unreachable,
    honor_env_platform,
)

honor_env_platform()
fall_back_to_cpu_if_unreachable(log=lambda s: print(s, file=sys.stderr))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402

GIB = 1 << 30


def _timed(fn, arg, iters: int) -> float:
    """Seconds per iteration of fn chained iters times, value-fetched.

    ``arg`` is the loop CARRY (a jit parameter), so the chain is
    loop-variant by construction — XLA cannot constant-fold the buffer
    or hoist the body out of the while loop (both verified against the
    compiled HLO; a captured ``jnp.zeros``/``ones`` closure would be
    folded to a broadcast and benchmark nothing).
    """
    chained = jax.jit(
        lambda x: lax.fori_loop(0, iters, lambda _, a: fn(a), x)
    )

    def fetch(out):
        # last leaf: for a (buffer, scalar) carry that is the scalar —
        # the value that data-depends on every iteration of the chain
        return float(jnp.ravel(jax.tree.leaves(out)[-1])[0])

    fetch(chained(arg))  # compile + warmup
    t0 = time.perf_counter()
    fetch(chained(arg))  # forces execution of the whole chain
    return (time.perf_counter() - t0) / iters


def main() -> None:
    dev = jax.devices()[0]
    print(f"device: {dev} platform={dev.platform}", file=sys.stderr)
    iters = int(os.environ.get("HBM_ITERS", "64"))

    # Fixed dispatch+fetch overhead of one timed call — through the axon
    # tunnel this is a network round trip (~10-100 ms), which deflates
    # every short chain: round-3's first run measured 43.5 "TFLOP/s" on
    # a 4-iter matmul chain purely because ~80 ms of RTT was folded into
    # ~23 ms of compute. Measured with the same _timed discipline on a
    # scalar body, then subtracted below; both raw and corrected values
    # are reported so the correction is auditable.
    rtt = _timed(lambda s: s + 1.0, jnp.zeros((), jnp.float32), 1)
    print(json.dumps({
        "metric": "dispatch_fetch_overhead_ms",
        "value": round(rtt * 1e3, 2), "unit": "ms",
        "platform": dev.platform,
    }))

    def corrected(per_iter: float, n_iters: int) -> float:
        # remove the one-off RTT amortized across the chain, floor at 10%
        # of the raw time so a misestimated RTT can't produce nonsense
        return max(per_iter - rtt / n_iters, per_iter * 0.1)

    n = GIB // 2  # 1 GiB of bf16
    x = jnp.zeros((n,), jnp.bfloat16)

    dt = _timed(lambda a: a + jnp.bfloat16(1), x, iters)
    stream = 2 * GIB / corrected(dt, iters)  # read + write
    print(json.dumps({
        "metric": "hbm_stream_gbps", "value": round(stream / 1e9, 1),
        "unit": "GB/s", "platform": dev.platform, "buffer_gib": 1.0,
        "iters": iters, "raw_gbps": round(2 * GIB / dt / 1e9, 1),
    }))

    # read-reduce: the buffer rides in the carry so it stays a jit
    # parameter (a captured closure constant would be folded), and the
    # reduce is scaled by a carry-derived 1 (s*0+1 — not foldable for
    # floats, NaN/inf semantics) so each iteration's 1 GiB read is
    # loop-variant and LICM cannot hoist it out of the while loop
    def _reduce(carry):
        buf, s = carry
        one = (s * 0 + 1).astype(buf.dtype)
        return buf, s + (buf * one).sum(dtype=jnp.float32)

    dt = _timed(_reduce, (x, jnp.zeros((), jnp.float32)), iters)
    print(json.dumps({
        "metric": "hbm_reduce_gbps",
        "value": round(GIB / corrected(dt, iters) / 1e9, 1),
        "unit": "GB/s", "platform": dev.platform,
        "raw_gbps": round(GIB / dt / 1e9, 1),
    }))

    # host->device transfer bandwidth: the fed-window denominator. A
    # batch-256 ResNet input is ~77 MB; fed steps/sec is bounded by
    # transfer_bw / batch_bytes no matter how the dispatch is arranged,
    # so this one number decides "tunnel artifact vs framework defect"
    # for the pipeline-fed efficiency rows (VERDICT r2 item 2).
    import numpy as _np
    # random bytes: a zeros buffer would let any compressing/deduping
    # relay path transfer ~nothing and report compression, not bandwidth
    host_buf = _np.random.default_rng(0).integers(
        0, 256, 64 << 20, dtype=_np.uint8)  # 64 MiB
    jax.device_put(host_buf).block_until_ready()  # warm the path
    reps = 3
    t0 = time.perf_counter()
    for _ in range(reps):
        # += 1 defeats any content-hash/dedup cache on the relay path
        host_buf[:4096] += 1
        jax.device_put(host_buf).block_until_ready()
    dt = (time.perf_counter() - t0) / reps
    print(json.dumps({
        "metric": "host_to_device_gbps",
        "value": round(len(host_buf) / dt / 1e9, 3), "unit": "GB/s",
        "platform": dev.platform, "buffer_mib": 64,
    }))

    m = int(os.environ.get("MXU_DIM", "8192"))
    a = jnp.full((m, m), 1.0, jnp.bfloat16)
    # b @ b keeps both operands loop-variant; the 1/m rescale pins
    # values at 1.0 so bf16 never overflows across iterations (the
    # elementwise write is ~0.03% of the matmul time)
    scale = jnp.bfloat16(1.0 / m)
    mm_iters = max(16, iters // 4)
    dt = _timed(lambda b: (b @ b) * scale, a, mm_iters)
    tflops = 2 * m**3 / corrected(dt, mm_iters) / 1e12
    print(json.dumps({
        "metric": "mxu_bf16_tflops", "value": round(tflops, 1),
        "unit": "TFLOP/s", "platform": dev.platform, "dim": m,
        "iters": mm_iters,
        "raw_tflops": round(2 * m**3 / dt / 1e12, 1),
        "pct_of_v5e_spec": round(tflops / 197 * 100, 1),
    }))


if __name__ == "__main__":
    main()
