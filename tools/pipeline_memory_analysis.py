#!/usr/bin/env python
"""Measure the pipelined BERT-base train step's per-device memory across
the (M microbatches, S stages, V virtual) grid — the VERDICT r2 item 3
evidence for "GPipe(+interleave)+remat fits the pod shapes" vs needing a
hand-scheduled 1F1B.

Why this matters: autodiff-through-scan retains one stage-IO activation
buffer per in-flight microbatch — O(M) per device (GPipe), where 1F1B
holds O(S). The question is whether O(M) at the BERT-pod shapes
(BASELINE.json:10, SURVEY §7 M8) actually presses the 16 GiB v5e HBM.
This tool compiles the REAL pipelined train step (same code path as
workloads/bert_pretrain with --mesh.pipe) on a fake CPU device mesh and
reads XLA's memory analysis. CPU-backend caveat: buffer ALLOCATION sizes
(activations, params, opt state) are layout-portable and dominate the
answer; TPU-specific padding/fusion shifts the total by O(10%), so read
the table with that error bar — it resolves "fits vs doesn't" except
within ~10% of the boundary.

Usage:  python tools/pipeline_memory_analysis.py [--quick]
  default grid: S in {2,4} x V in {1,2} x M in {8,16,32}, BERT-base,
  global batch 256 (so per-microbatch size varies with M), seq 512.
  --quick shrinks to a smoke grid for tests.

Prints one JSON line per config:
  {"S":..,"V":..,"M":..,"per_device_bytes":..,"gib":..,"fits_v5e":..}
plus a markdown table on stderr for PERF_NOTES.md.
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

V5E_HBM_GIB = 16.0


def analyze(S: int, V: int, M: int, *, batch: int, seq: int, cfg,
            data_ax=1, mlm=True):
    import jax
    import jax.numpy as jnp
    import optax

    from distributed_tensorflow_tpu.models import transformer as tfm
    from distributed_tensorflow_tpu.parallel import MeshSpec, build_mesh
    from distributed_tensorflow_tpu.parallel import sharding as sh
    from distributed_tensorflow_tpu.train import (
        StepOptions, init_train_state, jit_train_step, make_train_step,
    )
    from jax.sharding import NamedSharding

    mesh = build_mesh(MeshSpec(pipe=S, data=data_ax),
                      jax.devices()[: S * data_ax])
    init_fn = tfm.make_pipelined_init_fn(cfg, n_stages=S, seq_len=seq,
                                         n_virtual=V)
    specs = tfm.pipeline_param_specs(
        jax.eval_shape(init_fn, jax.random.PRNGKey(0))[0]
    )
    tx = optax.adamw(1e-4)
    state, sspecs = init_train_state(
        init_fn, tx, mesh, jax.random.PRNGKey(0), param_specs=specs,
    )
    piped = (tfm.pipelined_mlm_loss_fn if mlm else tfm.pipelined_lm_loss_fn)
    step = make_train_step(
        piped(cfg, mesh, n_microbatches=M, n_virtual=V),
        tx, StepOptions(),
    )
    jitted = jit_train_step(step, mesh, sspecs)
    if mlm:
        # gathered-head MLM format — the bert_pretrain default; K from
        # the ONE definition of the auto rule (data/text.py)
        from distributed_tensorflow_tpu.data.text import (
            TextDataConfig, resolved_max_predictions,
        )

        K = resolved_max_predictions(
            TextDataConfig(seq_len=seq, max_predictions=-1))
        batch_tree = {
            "input_ids": jnp.zeros((batch, seq), jnp.int32),
            "masked_positions": jnp.tile(jnp.arange(K, dtype=jnp.int32),
                                         (batch, 1)),
            "masked_labels": jnp.zeros((batch, K), jnp.int32),
        }
    else:
        # causal-LM: labels are shifted input_ids inside the loss
        batch_tree = {"input_ids": jnp.zeros((batch, seq), jnp.int32)}
    batch_tree = jax.tree.map(
        lambda x: jax.device_put(
            x, NamedSharding(mesh, sh.batch_spec(x.ndim))), batch_tree,
    )
    compiled = jitted.lower(state, batch_tree).compile()
    mem = compiled.memory_analysis()
    # per-device working set: XLA reports whole-program allocation stats
    total = (mem.temp_size_in_bytes + mem.argument_size_in_bytes
             + mem.output_size_in_bytes - mem.alias_size_in_bytes)
    return {
        "S": S, "V": V, "M": M,
        "temp_bytes": int(mem.temp_size_in_bytes),
        "arg_bytes": int(mem.argument_size_in_bytes),
        "per_device_bytes": int(total),
        "gib": round(total / 2**30, 2),
        "fits_v5e": total / 2**30 < V5E_HBM_GIB * 0.9,  # 10% headroom
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="tiny smoke grid (tests)")
    ap.add_argument("--pod", action="store_true",
                    help="16-device pod-shape grid (VERDICT r3 item 7): "
                         "BERT-base over pipe=4 x data=4, global batch "
                         "1024 — the pod-like M/S/V statement")
    ap.add_argument("--check", metavar="JSON",
                    help="single-config estimate for the runner's "
                         "pipeline-memory guard (VERDICT r4 item 8a): "
                         '{"model": <TransformerConfig dict>, "S":, '
                         '"V":, "M":, "batch":, "seq":, "mlm":}. '
                         "Prints ONE JSON row.")
    args = ap.parse_args()

    os.environ["JAX_PLATFORMS"] = "cpu"
    if args.check:
        req = json.loads(args.check)
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={req['S']}"
        ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")

        from distributed_tensorflow_tpu.models import transformer as tfm
        from distributed_tensorflow_tpu.utils import config as config_lib

        cfg = config_lib.from_dict(tfm.TransformerConfig, req["model"])
        row = analyze(req["S"], req["V"], req["M"], batch=req["batch"],
                      seq=req["seq"], cfg=cfg, data_ax=1,
                      mlm=bool(req.get("mlm", True)))
        print(json.dumps(row), flush=True)
        return

    n_dev = 16 if args.pod else 8
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={n_dev}"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    from distributed_tensorflow_tpu.models import transformer as tfm

    if args.quick:
        cfg = tfm.TransformerConfig(
            vocab_size=512, max_len=64, num_layers=4, d_model=64,
            num_heads=4, d_ff=128, causal=False, pre_ln=False,
            dtype="float32", remat=True,
        )
        grid = [(2, 1, 8), (2, 2, 8)]
        batch, seq = 32, 64
    elif args.pod:
        cfg = tfm.bert_base()
        # S=4 x data=4 over 16 devices at pod global batch 1024; V=3
        # is the deep-interleave point (S*V=12 = num_layers), M up to 64
        # probes the O(M) retention term at 4x the round-3 microbatches
        grid = [(4, V, M) for V in (1, 3) for M in (16, 32, 64)]
        batch, seq = 1024, 512
    else:
        cfg = tfm.bert_base()
        # S*V must divide num_layers=12: V=2 pairs with S=2 only; V=3
        # covers the deep-interleave point at both stage counts
        grid = [(S, V, M)
                for S in (2, 4) for V in (1, 3) for M in (8, 16, 32)]
        grid += [(2, 2, M) for M in (8, 16, 32)]
        batch, seq = 256, 512

    rows = []
    for S, V, M in grid:
        try:
            r = analyze(S, V, M, batch=batch, seq=seq, cfg=cfg,
                        data_ax=n_dev // S if not args.quick else 2)
        except Exception as e:  # keep the sweep alive; report the hole
            r = {"S": S, "V": V, "M": M, "error": str(e)[:200]}
        rows.append(r)
        print(json.dumps(r), flush=True)

    print("\n| S | V | M | per-device GiB | fits v5e (14.4 GiB usable) |",
          file=sys.stderr)
    print("|---|---|---|---|---|", file=sys.stderr)
    for r in rows:
        if "error" in r:
            print(f"| {r['S']} | {r['V']} | {r['M']} | ERROR | — |",
                  file=sys.stderr)
        else:
            print(f"| {r['S']} | {r['V']} | {r['M']} | {r['gib']} | "
                  f"{'yes' if r['fits_v5e'] else 'NO'} |", file=sys.stderr)


if __name__ == "__main__":
    main()
