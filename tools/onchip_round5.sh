#!/bin/bash
# Round-5 on-chip session — the round-4 queue (tools/onchip_round4.sh)
# restructured into TIERS (VERDICT r4 item 2): the only healthy window
# ever observed lasted 41 minutes, so the decisive questions must land
# in a guaranteed <=25-minute prefix, with everything else best-effort.
#
#   TIER A (worst-case 25 min; measured expectation ~14 min from the r3
#   window: probe 16 s, hbm ~40 s, bench ~3 min/variant, bert ~4 min):
#     probe -> corrected RTT-subtracted roofline -> flagship auto-A/B
#     -> first BERT row.  Artifacts are committed the moment the tier
#     completes.
#   TIER B (best-effort, value-per-minute order): first GPT/4k/W&D
#     numbers, fed-window proof, validator, kernel-tier A/Bs, the six
#     transformer knob A/Bs, microbenches, profile.
#
# A step that hits its timeout triggers a cheap relay re-probe; a dead
# relay ABORTS the session instead of burning every remaining step's
# timeout hung (the r2/r3 outage signature is multi-hour — nothing
# after the death would have succeeded anyway; all finished logs are
# already preserved in-tree).
#
# Runs under tools/chip_session.sh (the watcher wraps it), so every
# framework-importing python on the host pins itself to CPU for the
# duration (utils/chip_lock.py).
#
# DTF_SESSION_DRYRUN=1: CPU rehearsal of TIER A only — continues past a
# down relay (each bench takes its honest CPU-fallback path), skips the
# git commits, and prints the tier's wall-clock so the <=25-min budget
# claim is demonstrated without hardware (VERDICT r4 item 2).
# Usage: bash tools/onchip_round5.sh [outdir]   (default /tmp/onchip_r5)
set -u
cd "$(dirname "$0")/.."
OUT=$(readlink -f "${1:-/tmp/onchip_r5}")
mkdir -p "$OUT"
DRY=${DTF_SESSION_DRYRUN:-}
T0=$(date +%s)

ART="artifacts/onchip_r5"
if [ -n "$DRY" ]; then
  ART="$OUT/art_dry"  # rehearsal logs stay out of tree
  # ...and rehearsal probes stay out of the REAL probe cache: a dryrun
  # on a host without the chip would otherwise write DOWN and make the
  # driver's bench skip a genuinely healthy window for the whole TTL
  export DTF_PROBE_CACHE="$OUT/probe_cache.json"
fi
mkdir -p "$ART"

commit_art() { # milestone
  if [ -n "$DRY" ]; then echo "    (dryrun: skipping commit: $1)"; return; fi
  git add "$ART" >/dev/null 2>&1
  git commit -q -m "Round-5 on-chip artifacts: $1" -- "$ART" \
    >/dev/null 2>&1 && echo "    committed: $1"
}

run() { # name timeout_s cmd...
  local name=$1 t=$2; shift 2
  echo "=== $name ($(date -u +%H:%M:%S)) ==="
  timeout --signal=TERM --kill-after=60 "$t" "$@" \
    >"$OUT/$name.log" 2>&1
  local rc=$?
  echo "    rc=$rc  tail:"
  tail -3 "$OUT/$name.log" | sed 's/^/    /'
  # preserve in-tree IMMEDIATELY: the relay has died mid-session twice;
  # only committed files survive a round end
  cp "$OUT/$name.log" "$ART/${name}.log" 2>/dev/null
  # rc=124 = TERM on timeout; rc>=128 includes 137 = --kill-after
  # SIGKILL of a step that wedged in backend RPC and ignored TERM —
  # both are the hang signature, and missing the second would let every
  # remaining step burn its full timeout against a dead relay
  if [ $rc -ge 124 ] && [ -z "$DRY" ]; then
    # step hung to its timeout — dead relay, or just a slow step?
    if ! python -u tools/probe.py 90 >>"$OUT/reprobe.log" 2>&1; then
      echo "!!! relay dead after $name; aborting session (logs kept)"
      cp "$OUT/reprobe.log" "$ART/reprobe.log" 2>/dev/null
      python tools/summarize_onchip.py "$OUT" >"$ART/DIGEST.md" \
        2>/dev/null  # partial digest: whatever landed before the death
      commit_art "aborted after $name (relay died mid-session)"
      exit 95
    fi
  fi
  return $rc
}

# ---------------- TIER A: decisive prefix, worst case 25 min ----------
# Worst-case budget: 200 + 280 + 700 + 320 = 1500 s. Healthy-path
# expectation ~15 min (probe 16 s, hbm ~2 min, bench A/B ~9 min,
# bert ~4 min — r3 window timings).
# 1. probe — inner 90 s x2 attempts must finish INSIDE the outer budget
#    or the verdict never reaches the shared cache (r5 dryrun lesson)
run probe 200 python -u tools/probe.py 90 \
  || { if [ -z "$DRY" ]; then echo 'relay down; aborting session'; exit 1;
       else echo '    (dryrun: continuing past down relay)'; fi; }
# The session just proved the relay healthy: every bench below skips
# its own probe ladder (a healthy->dead transition instead surfaces as
# a step timeout, which the rc=124 reprobe-abort above handles).
[ -z "$DRY" ] && export BENCH_SKIP_PROBE=1

# 2. corrected roofline: RTT-subtracted HBM/MXU + host->device bandwidth
#    — decides whether 0.50 MFU is chip-bound or program-bound here
run hbm 280 env HBM_ITERS=64 python -u tools/bench_hbm.py

# 3. flagship bench — unpinned: A/Bs fused-vs-standard, reports the
#    faster (measured ~3 min/variant in r3 => ~9 min for A/B + winner)
run bench_auto 700 python -u bench.py
LATEST=$(grep -h '"metric"' "$OUT"/bench_auto.log 2>/dev/null | tail -1)
[ -n "$LATEST" ] && printf '%s\n' "$LATEST" > "$ART"/BENCH_LATEST.json

# 4. first-ever BERT row (MXU-bound tier; lost to the r3 lease collision
#    and the r4 outage)
run bert 320 python -u tools/bench_bert.py

commit_art "tier A complete (roofline + flagship A/B + BERT)"
echo "=== TIER A done in $(( $(date +%s) - T0 ))s (budget 1500s) ==="
if [ -n "$DRY" ]; then
  echo "dryrun complete (tier A only); logs in $OUT"
  exit 0
fi

# ---------------- TIER B: best-effort, value-per-minute order ---------
# first-ever GPT / long-context / embedding-tier numbers
run gpt_plain 900 env BENCH_MODEL=gpt python -u tools/bench_bert.py
run gpt_long4k 1200 env BENCH_MODEL=gpt BENCH_SEQ=4096 BENCH_BATCH=4 \
  BENCH_REMAT=1 python -u tools/bench_bert.py
run wide_deep 900 python -u tools/bench_wide_deep.py

# scaling observatory (ISSUE 11): the first on-chip dtf-scaling-1
# report — on one chip only the 1dev cells run (multi-dev cells are
# recorded as skipped, never silently elided), but every number lands
# provenance-stamped (platform/device_kind/git_sha), so this row can
# never be confused with the CPU-rig curves the way BENCH_r02-r05 were
run sweep_scaling 900 python -u tools/sweep.py \
  --workloads mlp,gpt --eval-batches 2 --out "$ART/SCALING_r5.json"

# fed-window proof (VERDICT r3 item 3): jpeg-decode-fed and the
# PUT_SYNC A/B in the same session; hbm above already reported
# host_to_device_gbps, making these rows self-explaining
run bench_jpeg 1200 env BENCH_DATA=jpeg python -u bench.py
run bench_jpeg_putsync 1200 env BENCH_DATA=jpeg BENCH_PUT_SYNC=1 \
  python -u bench.py

commit_art "tier B: model families + fed windows"

# validator incl. the bench-shape compile/execute sweep
run validate 1200 python -u tools/validate_fused_tpu.py

# kernel-tier verdict rows (bench_auto already picked a winner; these
# give clean single-variable logs + the Pallas-backward datum)
run bench_fused_xlabwd 900 env BENCH_BLOCK_IMPL=fused python -u bench.py
run bench_fused_pallasbwd 900 env BENCH_BLOCK_IMPL=fused \
  DTF_FUSED_BWD=pallas python -u bench.py
run bench_standard 900 env BENCH_BLOCK_IMPL=standard python -u bench.py

# six transformer knob A/Bs (r4 levers, all parity-tested, none measured)
run bert_fused_qkv 900 env BENCH_FUSED_QKV=1 python -u tools/bench_bert.py
run gpt_head_bf16 900 env BENCH_MODEL=gpt BENCH_HEAD_DTYPE=bfloat16 \
  python -u tools/bench_bert.py
run gpt_dense_xent 900 env BENCH_MODEL=gpt BENCH_XENT_CHUNK=0 \
  python -u tools/bench_bert.py
run gpt_b64 900 env BENCH_MODEL=gpt BENCH_BATCH=64 BENCH_REMAT=1 \
  python -u tools/bench_bert.py
run bert_remat 900 env BENCH_REMAT=1 python -u tools/bench_bert.py
run bert_b256 900 env BENCH_BATCH=256 BENCH_REMAT=1 \
  python -u tools/bench_bert.py

commit_art "tier B: kernel-tier + knob A/Bs"

# flash block sweep + attention ablations
run bert_wide_flash 900 env DTF_FLASH_BLOCK_Q=256 DTF_FLASH_BLOCK_K=512 \
  python -u tools/bench_bert.py
run bert_dense_attn 900 env BENCH_ATTN=dense python -u tools/bench_bert.py
run gpt_fused_ln 900 env BENCH_MODEL=gpt BENCH_FUSED_LN=1 \
  python -u tools/bench_bert.py
run gpt_long4k_k512 1200 env BENCH_MODEL=gpt BENCH_SEQ=4096 BENCH_BATCH=4 \
  BENCH_REMAT=1 DTF_FLASH_BLOCK_Q=128 DTF_FLASH_BLOCK_K=512 \
  python -u tools/bench_bert.py

# per-shape kernel microbenches: fwd (pallas won 1.0-2.5x in r3,
# re-confirm) and grad with the single-pass backward (grad is
# stall-prone — r3 s3_conv1 rc=124; the step timeout contains it)
run microbench_fwd 900 python -u tools/bench_fused_kernels.py fwd
run microbench_grad 900 env DTF_FUSED_BWD=pallas \
  python -u tools/bench_fused_kernels.py grad

# profile capture at bench config (fused fwd + XLA bwd)
rm -rf "$OUT/profile"
run profile 1200 python -u examples/train.py resnet50_imagenet \
  --train.num_steps=30 --train.profile=true \
  --train.profile_dir="$OUT/profile" \
  --model.norm_dtype=bfloat16 --model.stem=space_to_depth \
  --model.block_impl=fused --data.global_batch_size=256 \
  --data.image_size=224 --checkpoint.directory= \
  --train.log_every=10
tar -C "$OUT" -czf "$OUT/profile.tgz" profile 2>/dev/null \
  && cp "$OUT/profile.tgz" "$ART/profile_r5.tgz" \
  && echo "    profile.tgz $(du -h "$OUT/profile.tgz" | cut -f1)"

# LAST (can stall): AOT-compile the non-default Pallas backward at every
# bench shape
run validate_pallas_bwd 1200 env VALIDATE_PALLAS_BWD=only \
  python -u tools/validate_fused_tpu.py

echo "=== session done; JSON lines: ==="
grep -h '"metric"' "$OUT"/*.log 2>/dev/null
# digest lands WITH the artifacts: even a session that ends after the
# last builder turn ships its own analysis (stdlib-only, no device use)
python tools/summarize_onchip.py "$OUT" >"$ART/DIGEST.md" 2>/dev/null \
  && echo "    digest -> $ART/DIGEST.md"
echo "logs in $OUT; artifacts in $ART"
commit_art "session complete"
