#!/bin/bash
# HISTORICAL (round-4 watcher; superseded by tools/tpu_watch_r5.sh,
# which probes through the canonical tools/probe.py shared cache and
# re-arms after incomplete sessions — use that one).
# Round-4 relay watcher: probe the tunneled TPU every ~4 min; at the first
# healthy window take the chip-session lock and fire tools/onchip_round4.sh.
# Exits when a session has been captured (or the deadline passes) so the
# invoking shell gets control back.
# Usage: bash tools/tpu_watch_r4.sh [deadline_epoch_s]
set -u
cd "$(dirname "$0")/.."
DEADLINE=${1:-$(($(date +%s) + 11*3600))}
LOG=/tmp/tpu_watch_r4.log
echo "watcher start $(date -u +%F' '%T) deadline $(date -u -d @"$DEADLINE" +%T)" | tee -a "$LOG"

probe() {
  # never probe while a chip session is live: the probe is a bare
  # `import jax` (outside the chip_lock guard) and would contend for the
  # single lease — the round-3 failure class. flock released => no session.
  local LOCKF="${DTF_CHIP_LOCK:-/tmp/dtf_chip_session.lock}.flock"
  if [ -e "$LOCKF" ] && ! flock -n "$LOCKF" true; then
    echo "    chip session live; skipping probe" >>"$LOG"
    return 1
  fi
  timeout --signal=TERM --kill-after=30 150 python -u -c "
import jax, jax.numpy as jnp
d = jax.devices()
assert d and d[0].platform == 'tpu', d
print('PROBE-OK', d, float(jax.jit(lambda a:(a@a).sum())(jnp.ones((256,256),jnp.bfloat16))))
" >>"$LOG" 2>&1
}

n=0
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  n=$((n+1))
  echo "--- probe $n $(date -u +%T)" >>"$LOG"
  if probe; then
    echo "=== RELAY UP at probe $n ($(date -u +%T)); firing onchip_round4.sh ===" | tee -a "$LOG"
    bash tools/chip_session.sh bash tools/onchip_round4.sh /tmp/onchip_r4 \
      >>"$LOG" 2>&1
    rc=$?
    echo "=== session rc=$rc ($(date -u +%T)) ===" | tee -a "$LOG"
    # commit the evidence immediately: only committed files survive a
    # round end, and the session may land with no builder turns left
    git add artifacts/onchip_r4 >>"$LOG" 2>&1
    # pathspec-restricted: must not sweep unrelated staged work into the
    # auto-commit (ADVICE r4)
    git commit -m "Round-4 on-chip session artifacts (auto-committed by the relay watcher)" \
      -- artifacts/onchip_r4 >>"$LOG" 2>&1 \
      || echo "watcher: nothing to commit" >>"$LOG"
    exit $rc
  fi
  sleep 240
done
echo "watcher deadline passed without a healthy window" | tee -a "$LOG"
exit 99
