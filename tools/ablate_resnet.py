#!/usr/bin/env python
"""Ablation driver for the ResNet-50 bench (PERF_NOTES.md evidence).

Thin wrapper: each variant is a `bench.py` run with BENCH_* env overrides,
so timing methodology, FLOPs accounting (fwd-only × train multiplier), and
MFU math live in exactly one place — bench.py. One JSON line per variant
to stdout; bench diagnostics pass through on stderr.

Usage: python tools/ablate_resnet.py [variant ...]   (default: all)
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")

# Every variant pins ALL knobs explicitly (never inherits ambient BENCH_*
# from the operator's shell), and the pinned values are echoed into the
# output row, so a sweep can't be silently mislabeled.
_KNOBS = ("BENCH_STEM", "BENCH_NORM_DTYPE", "BENCH_DEBUG_METRICS",
          "BENCH_BATCH", "BENCH_STEPS", "BENCH_BLOCK_IMPL")


def _variant(stem="space_to_depth", norm="bfloat16", dbg="0", batch="256",
             steps="20", blocks="standard"):
    return {"BENCH_STEM": stem, "BENCH_NORM_DTYPE": norm,
            "BENCH_DEBUG_METRICS": dbg, "BENCH_BATCH": batch,
            "BENCH_STEPS": steps, "BENCH_BLOCK_IMPL": blocks}


VARIANTS = {
    "r1_baseline": _variant(stem="conv", norm="float32", dbg="1"),
    "no_metrics": _variant(stem="conv", norm="float32"),
    "bf16_bn": _variant(stem="conv"),
    "s2d_f32bn": _variant(norm="float32"),
    "combo256": _variant(),  # round-2a tuned config, standard blocks
    "combo384": _variant(batch="384"),
    "combo512": _variant(batch="512"),
    "combo1024": _variant(batch="1024"),
    # round-2b fused Pallas conv+BN blocks (ops/fused_conv_bn.py)
    "fused256": _variant(blocks="fused"),
    "fused384": _variant(blocks="fused", batch="384"),
    "fused512": _variant(blocks="fused", batch="512"),
}


def main() -> None:
    names = sys.argv[1:] or list(VARIANTS)
    for name in names:
        env = {k: v for k, v in os.environ.items() if k not in _KNOBS}
        env.update(VARIANTS[name])
        env["BENCH_SKIP_PROBE"] = "1"  # one sweep, one relay; skip per-run probes
        proc = subprocess.run(
            [sys.executable, BENCH], env=env, capture_output=True, text=True
        )
        sys.stderr.write(proc.stderr)
        if proc.returncode != 0:
            print(json.dumps({"variant": name, "error": proc.returncode}),
                  flush=True)
            continue
        result = json.loads(proc.stdout.strip().splitlines()[-1])
        print(json.dumps({"variant": name, **VARIANTS[name], **result}),
              flush=True)


if __name__ == "__main__":
    main()
