#!/usr/bin/env python
"""Ablation driver for the ResNet-50 bench (PERF_NOTES.md evidence).

Thin wrapper: each variant is a `bench.py` run with BENCH_* env overrides,
so timing methodology, FLOPs accounting (fwd-only × train multiplier), and
MFU math live in exactly one place — bench.py. One JSON line per variant
to stdout; bench diagnostics pass through on stderr.

Usage: python tools/ablate_resnet.py [variant ...]   (default: all)
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")

# name: BENCH_* env overrides
VARIANTS = {
    "r1_baseline": {"BENCH_STEM": "conv", "BENCH_NORM_DTYPE": "float32",
                    "BENCH_DEBUG_METRICS": "1"},
    "no_metrics": {"BENCH_STEM": "conv", "BENCH_NORM_DTYPE": "float32"},
    "bf16_bn": {"BENCH_STEM": "conv"},
    "s2d_f32bn": {"BENCH_NORM_DTYPE": "float32"},
    "combo256": {},  # the bench default config
    "combo384": {"BENCH_BATCH": "384"},
    "combo512": {"BENCH_BATCH": "512"},
    "combo1024": {"BENCH_BATCH": "1024"},
}


def main() -> None:
    names = sys.argv[1:] or list(VARIANTS)
    for name in names:
        env = {**os.environ, **VARIANTS[name]}
        proc = subprocess.run(
            [sys.executable, BENCH], env=env, capture_output=True, text=True
        )
        sys.stderr.write(proc.stderr)
        if proc.returncode != 0:
            print(json.dumps({"variant": name, "error": proc.returncode}),
                  flush=True)
            continue
        result = json.loads(proc.stdout.strip().splitlines()[-1])
        print(json.dumps({"variant": name, **VARIANTS[name], **result}),
              flush=True)


if __name__ == "__main__":
    main()
