#!/usr/bin/env python
"""Ablation harness for the ResNet-50 bench (VERDICT round-2 item 1).

Times train-step variants on the real chip to locate the MFU gap:
stem (conv vs space_to_depth), BN output dtype, debug-metric overhead,
batch size. Diagnostics to stderr, one JSON line per variant to stdout.

Usage: python tools/ablate_resnet.py [variant ...]
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def log(*a):
    print(*a, file=sys.stderr, flush=True)


VARIANTS = {
    # name: (batch, stem, norm_dtype, grad_norm+finite on)
    "r1_baseline": (256, "conv", "float32", True),
    "no_metrics": (256, "conv", "float32", False),
    "bf16_bn": (256, "conv", "bfloat16", False),
    "s2d": (256, "space_to_depth", "float32", False),
    "combo256": (256, "space_to_depth", "bfloat16", False),
    "combo512": (512, "space_to_depth", "bfloat16", False),
    "combo1024": (1024, "space_to_depth", "bfloat16", False),
}


def run_variant(name, batch, stem, norm_dtype, dbg):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding

    from distributed_tensorflow_tpu.models import common
    from distributed_tensorflow_tpu.models.resnet import (
        ResNet50, ResNetConfig, flops_per_example,
    )
    from distributed_tensorflow_tpu.parallel import MeshSpec, build_mesh
    from distributed_tensorflow_tpu.parallel import sharding as sh
    from distributed_tensorflow_tpu.train import (
        OptimizerConfig, StepOptions, init_train_state, jit_train_step,
        make_optimizer, make_train_step,
    )
    from distributed_tensorflow_tpu.utils import flops as flops_lib

    devices = jax.devices()
    image = 224
    cfg = ResNetConfig(stem=stem, norm_dtype=norm_dtype)
    mesh = build_mesh(MeshSpec(data=-1))
    model = ResNet50(cfg)
    loss_fn = common.classification_loss_fn(model)
    tx = make_optimizer(OptimizerConfig(
        name="momentum", learning_rate=0.1, momentum=0.9, weight_decay=1e-4,
    ))
    state, specs = init_train_state(
        common.make_init_fn(model, (image, image, 3)), tx, mesh,
        jax.random.PRNGKey(0),
    )
    opts = StepOptions(compute_grad_norm=dbg, check_grads_finite=dbg)
    step = jit_train_step(make_train_step(loss_fn, tx, opts), mesh, specs)

    rng = np.random.RandomState(0)
    bdata = {
        "image": rng.randn(batch, image, image, 3).astype(np.float32)
        .astype(jnp.bfloat16),
        "label": rng.randint(0, cfg.num_classes, batch).astype(np.int32),
    }
    bdata = jax.tree.map(
        lambda x: jax.device_put(x, NamedSharding(mesh, sh.batch_spec(np.ndim(x)))),
        bdata,
    )

    def sync(metrics):
        return float(jax.device_get(metrics["loss"]))

    t_c0 = time.perf_counter()
    for _ in range(3):
        state, metrics = step(state, bdata)
    sync(metrics)
    log(f"[{name}] compile+warmup {time.perf_counter() - t_c0:.1f}s")
    measured = int(os.environ.get("BENCH_STEPS", "20"))
    t0 = time.perf_counter()
    for _ in range(measured):
        state, metrics = step(state, bdata)
    loss = sync(metrics)
    dt = time.perf_counter() - t0
    assert np.isfinite(loss), name

    sps = measured / dt
    ips = sps * batch
    fl = flops_per_example(cfg, image) * batch
    peak = flops_lib.peak_flops_per_chip(devices[0])
    m = flops_lib.mfu(fl, sps, len(devices), peak)
    out = {"variant": name, "batch": batch, "stem": stem,
           "norm_dtype": norm_dtype, "debug_metrics": dbg,
           "images_per_sec": round(ips, 1), "step_ms": round(1e3 / sps, 2),
           "mfu": round(m, 4), "loss": round(loss, 4)}
    log(f"[{name}] {out}")
    print(json.dumps(out), flush=True)


def main():
    names = sys.argv[1:] or list(VARIANTS)
    for n in names:
        run_variant(n, *VARIANTS[n])


if __name__ == "__main__":
    main()
