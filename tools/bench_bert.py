#!/usr/bin/env python
"""Secondary benchmark: BERT-base MLM training throughput + MFU on the
available chip(s) — the BASELINE.json:10 workload, same honest timing
contract as the flagship bench.py (value-fetch sync, steady-state window
after warmup). Transformers are matmul-dominated, so unlike bandwidth-
bound ResNet-50 this measures how close the framework gets to the MXU
roofline.

Prints ONE JSON line to stdout; diagnostics to stderr.

Env knobs:
  BENCH_BATCH       PER-CHIP batch (default 128 on TPU, 8 on CPU) —
                    same semantics as the flagship bench.py
  BENCH_SEQ         sequence length (default 512, the reference's config)
  BENCH_STEPS       measured steps (default 20)
  BENCH_MODEL       "bert" (post-LN encoder MLM, default) | "gpt"
                    (pre-LN causal LM — the fused-LN showcase)
  BENCH_FUSED_LN    "1" to fuse LayerNorm into matmuls (pre-LN only,
                    i.e. BENCH_MODEL=gpt)
  BENCH_REMAT       "1" to jax.checkpoint each block (fit bigger batches)
  BENCH_ATTN        attention impl: "auto" (flash on TPU) | "dense" |
                    "blockwise" | "flash" — flash-vs-XLA-dense on chip
  BENCH_FUSED_QKV   "1" to project q/k/v with one [d, 3d] matmul
                    (megatron-style fused QKV) instead of three [d, d]
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main() -> None:
    import jax

    from distributed_tensorflow_tpu.utils import benchmarking as bm

    # honest CPU row instead of hanging forever on a dead relay
    bm.fall_back_to_cpu_if_unreachable(log=log)
    bm.honor_env_platform()
    import dataclasses

    import numpy as np

    from distributed_tensorflow_tpu.data.text import IGNORE_INDEX
    from distributed_tensorflow_tpu.models import transformer as tfm
    from distributed_tensorflow_tpu.parallel import MeshSpec, build_mesh, describe
    from distributed_tensorflow_tpu.parallel import sharding as sh
    from distributed_tensorflow_tpu.train import (
        OptimizerConfig, StepOptions, init_train_state, jit_train_step,
        make_optimizer, make_train_step,
    )
    from distributed_tensorflow_tpu.utils import flops as flops_lib

    devices, n_chips, platform, on_tpu = bm.describe_devices()
    log(f"bench devices: {devices} (platform={platform})")

    which = os.environ.get("BENCH_MODEL", "bert")
    fused_ln = os.environ.get("BENCH_FUSED_LN", "0") == "1"
    remat = os.environ.get("BENCH_REMAT", "0") == "1"
    seq = int(os.environ.get("BENCH_SEQ", "512"))
    # per-chip, like bench.py: the number scales with slice size instead
    # of silently shrinking per chip. GPT's default is smaller than
    # BERT's because the causal LM loss materializes full [B, S, vocab]
    # logits (every position is a target — no gathered head): at
    # B=128, S=512, V=50304 that is 13 GB in f32 before the backward,
    # far over a v5e's HBM. B=32 bounds the logits tier at ~8 GB
    # (bf16 + f32 + dlogits); BENCH_BATCH probes the knee either way.
    default_batch = ("8" if not on_tpu
                     else "32" if which == "gpt" else "128")
    per_chip_batch = int(os.environ.get("BENCH_BATCH", default_batch))
    global_batch = per_chip_batch * n_chips

    if which == "bert":
        cfg = tfm.bert_base()
        if fused_ln:
            raise SystemExit("BENCH_FUSED_LN needs BENCH_MODEL=gpt "
                             "(BERT is post-LN; the kernel is pre-LN-only)")
    elif which == "gpt":
        cfg = tfm.gpt_small(causal_len=max(seq, 512))
        cfg = dataclasses.replace(cfg, fused_ln_matmul=fused_ln)
    else:
        raise SystemExit(f"unknown BENCH_MODEL={which!r}")
    if not on_tpu:  # tiny fallback so the CPU smoke run finishes fast
        cfg = dataclasses.replace(
            cfg, num_layers=2, d_model=128, num_heads=4, d_ff=256,
            vocab_size=1024, max_len=max(seq, 128), dtype="float32",
        )
    attn = os.environ.get("BENCH_ATTN", "auto")
    fused_qkv = os.environ.get("BENCH_FUSED_QKV", "0") == "1"
    # BENCH_HEAD_DTYPE=bfloat16 runs the tied-embedding vocab projection
    # on the fast MXU tier (f32 accumulation) — the ~25-30%-of-FLOPs
    # GPT head currently runs f32 at ~1/4 rate; f32 default = exact path
    head_dtype = os.environ.get("BENCH_HEAD_DTYPE", "float32")
    cfg = dataclasses.replace(cfg, remat=remat, attention_impl=attn,
                              fused_qkv=fused_qkv, head_dtype=head_dtype)
    if seq > cfg.max_len:
        raise SystemExit(f"BENCH_SEQ={seq} > max_len={cfg.max_len}")

    mesh = build_mesh(MeshSpec(data=-1))
    log(f"mesh: {describe(mesh)}  model={which} fused_ln={fused_ln} "
        f"attn={attn} seq={seq} global_batch={global_batch}")

    model = tfm.Transformer(cfg, mesh)
    # BENCH_XENT_CHUNK (gpt only): chunk size for the sequence-chunked
    # causal-LM loss — default 128 keeps peak logits memory at
    # [B, 128, vocab] instead of [B, S, vocab]; 0 = dense loss A/B.
    # The default only engages when it divides BENCH_SEQ (a default must
    # not make previously-valid seq lengths fail); an explicit env value
    # stays strict and raises on non-dividing shapes.
    default_chunk = "128" if which == "gpt" and seq % 128 == 0 else "0"
    xent_chunk = int(os.environ.get("BENCH_XENT_CHUNK", default_chunk))
    loss_fn = tfm.mlm_loss_fn(model) if which == "bert" \
        else tfm.causal_lm_loss(model, xent_chunk)
    tx = make_optimizer(OptimizerConfig(
        name="adamw", learning_rate=1e-4, weight_decay=0.01,
    ))
    state, specs = init_train_state(
        tfm.make_init_fn(model, seq), tx, mesh, jax.random.PRNGKey(0),
        param_rules=tfm.transformer_rules(cfg),
    )
    step = jit_train_step(
        make_train_step(loss_fn, tx, StepOptions()), mesh, specs,
    )

    rng = np.random.RandomState(0)
    from jax.sharding import NamedSharding

    ids = rng.randint(0, cfg.vocab_size, (global_batch, seq)).astype(np.int32)
    batch = {"input_ids": ids}
    if which == "bert":
        if os.environ.get("BENCH_MLM_DENSE") == "1":
            # legacy dense-labels head: vocab projection on all seq
            # positions (the pre-gather behavior, kept for ablation)
            batch["labels"] = np.where(
                rng.rand(global_batch, seq) < 0.15, ids, IGNORE_INDEX
            ).astype(np.int32)
        else:
            # gathered MLM head — the bert_pretrain workload default;
            # K from the ONE definition of the auto rule
            from distributed_tensorflow_tpu.data.text import (
                TextDataConfig, resolved_max_predictions,
            )

            K = resolved_max_predictions(
                TextDataConfig(seq_len=seq, max_predictions=-1))
            pos = np.sort(
                np.argsort(rng.rand(global_batch, seq), axis=1)[:, :K],
                axis=1,
            ).astype(np.int32)
            batch["masked_positions"] = pos
            batch["masked_labels"] = np.take_along_axis(ids, pos, axis=1)
    batch = jax.tree.map(
        lambda x: jax.device_put(
            x, NamedSharding(mesh, sh.batch_spec(np.ndim(x)))
        ),
        batch,
    )

    measured = int(os.environ.get("BENCH_STEPS", "20"))
    state, steps_per_sec, final_loss = bm.timed_steps(
        step, state, lambda: batch, warmup=3, measured=measured, log=log,
    )
    examples_per_sec_per_chip = steps_per_sec * global_batch / n_chips
    n_pred = (batch["masked_positions"].shape[1]
              if "masked_positions" in batch else None)
    # shared MFU helper (obs/goodput.py): applies the fwd+bwd multiplier
    from distributed_tensorflow_tpu.obs import goodput

    peak = flops_lib.peak_flops_per_chip(devices[0])
    mfu = goodput.train_mfu(
        tfm.flops_per_example(cfg, seq, n_predictions=n_pred) * global_batch,
        steps_per_sec, n_chips=n_chips, peak_per_chip=peak,
    )
    log(f"steps/sec={steps_per_sec:.3f} "
        f"examples/sec/chip={examples_per_sec_per_chip:.1f} MFU={mfu:.3f}")

    print(json.dumps({
        "metric": f"{which}_examples_per_sec_per_chip",
        "value": round(examples_per_sec_per_chip, 2),
        "unit": "examples/sec/chip",
        "vs_baseline": round(mfu / 0.50, 4),
        "mfu": round(mfu, 4),
        "platform": platform,
        "n_chips": n_chips,
        "global_batch": global_batch,
        "seq_len": seq,
        "model": which,
        "fused_ln_matmul": fused_ln,
        "fused_qkv": fused_qkv,
        "xent_chunk": xent_chunk,
        "head_dtype": head_dtype,
        "attention_impl": attn,
        "mlm_predictions": n_pred,  # None = dense head / causal LM
        "full_size_model": bool(on_tpu),
    }))


if __name__ == "__main__":
    main()
