#!/usr/bin/env bash
# Import/collection smoke gate — seconds, not minutes.
#
# `pytest --collect-only` imports every test module (and through them the
# whole package) without running a single test, so an import regression —
# like the `from jax import shard_map` breakage this gate was added for
# (ISSUE 1) — fails loudly here instead of silently dropping two modules
# from the suite. Run it before pushing; CI runs it before the full suite.
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python -m pytest tests/ --collect-only -q \
    -p no:cacheprovider "$@"
