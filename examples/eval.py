#!/usr/bin/env python
"""Standalone eval entry point — restore the latest checkpoint and report
metrics without training (SURVEY.md §3.5: the reference ran eval
single-process from `latest_checkpoint`, $TF checkpoint_management.py:329).

Usage:
    python examples/eval.py mnist_mlp --checkpoint.directory=/tmp/ck
    python examples/eval.py resnet50_imagenet \
        --checkpoint.directory=/ckpts/run1 --train.eval_batches=64
"""

import logging
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from distributed_tensorflow_tpu import workloads


def main(argv: list[str]) -> None:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s: %(message)s",
        datefmt="%H:%M:%S",
        force=True,
    )
    if not argv or argv[0].startswith("-"):
        print(f"usage: eval.py <workload> --checkpoint.directory=... "
              f"[--section.key=value ...]\n"
              f"workloads: {', '.join(workloads.available())}")
        raise SystemExit(2)
    name, overrides = argv[0], [a for a in argv[1:] if a.startswith("--")]
    metrics = workloads.eval_workload(name, overrides)
    print(f"eval: {metrics}")


if __name__ == "__main__":
    main(sys.argv[1:])
