#!/usr/bin/env python
"""Train-script entry point — the user-facing analog of the reference's
per-workload scripts (SURVEY.md §2a flag layer).

Usage:
    python examples/train.py mnist_mlp --train.num_steps=500
    python examples/train.py cifar10_cnn --mesh.data=8 --optimizer.learning_rate=0.1
    python examples/train.py resnet50_imagenet --checkpoint.directory=/tmp/ck

Where the reference took ``--job_name/--task_index/--ps_hosts/--worker_hosts``
per process, here every host runs the same command; topology is
``--mesh.<axis>=<size>`` and multi-host bootstrap is automatic (or via
COORDINATOR_ADDRESS for manual clusters).
"""

import logging
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from distributed_tensorflow_tpu import workloads


def main(argv: list[str]) -> None:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s: %(message)s",
        datefmt="%H:%M:%S",
        force=True,  # imported libs (absl/orbax) may have claimed root already
    )
    if not argv or argv[0].startswith("-"):
        print(f"usage: train.py <workload> [--section.key=value ...]\n"
              f"workloads: {', '.join(workloads.available())}")
        raise SystemExit(2)
    name, overrides = argv[0], [a for a in argv[1:] if a.startswith("--")]
    result = workloads.run_workload(name, overrides)
    final = result.history[-1] if result.history else {}
    print(f"done: step={int(result.state.step)} last_metrics={final} "
          f"eval={result.eval_metrics}")


if __name__ == "__main__":
    main(sys.argv[1:])
