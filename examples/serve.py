#!/usr/bin/env python
"""Serving-entry demo — the inference sibling of examples/train.py.

Loads a tiny random-weight causal decoder, submits a few token-id
prompts, and streams greedy completions from the continuous-batching
engine (there is no tokenizer in this framework — prompts and outputs
are vocabulary ids, which is all the serving stack deals in). The
engine defaults to the PAGED KV cache (block pool + copy-on-write
prefix reuse + chunked prefill — docs/serving.md); ``--dense`` is the
one-flag escape hatch back to the PR-1 slot-dense cache.

Usage:
    JAX_PLATFORMS=cpu python examples/serve.py
    python examples/serve.py --prompts 5 --max-new 24 --temperature 0.8
    python examples/serve.py --dense   # slot-dense fallback
"""

import argparse
import random
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--prompts", type=int, default=3,
                    help="number of random prompts to submit")
    ap.add_argument("--max-new", type=int, default=16)
    ap.add_argument("--slots", type=int, default=2,
                    help="decode-batch slots (fewer than prompts shows "
                         "queueing + slot reuse)")
    ap.add_argument("--temperature", type=float, default=0.0,
                    help="0 = greedy")
    ap.add_argument("--top-k", type=int, default=0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--dense", action="store_true",
                    help="escape hatch: the PR-1 slot-dense KV cache "
                         "instead of the default paged block pool")
    ap.add_argument("--block-size", type=int, default=16,
                    help="tokens per KV block (paged cache)")
    ap.add_argument("--prefill-chunk", type=int, default=32,
                    help="prefill chunk size (paged cache)")
    args = ap.parse_args(argv)

    from distributed_tensorflow_tpu import serve
    from distributed_tensorflow_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(
        vocab_size=256, max_len=128, num_layers=2, d_model=64, num_heads=4,
        d_ff=128, dropout=0.0, dtype="float32", causal=True, pre_ln=True,
    )
    eng = serve.ServeEngine.with_random_params(
        cfg, seed=args.seed, num_slots=args.slots,
        temperature=args.temperature, top_k=args.top_k,
        paged=not args.dense, block_size=args.block_size,
        prefill_chunk=args.prefill_chunk,
    )

    rng = random.Random(args.seed)
    prompts = [
        [rng.randrange(cfg.vocab_size) for _ in range(rng.randint(3, 10))]
        for _ in range(args.prompts)
    ]
    uids = {
        eng.submit(p, max_new_tokens=args.max_new): p for p in prompts
    }
    print(f"submitted {len(prompts)} prompts into {args.slots} slots\n")

    # drive the engine step by step, streaming tokens as they land
    while eng.sched.has_work:
        stats = eng.step()
        for uid, tok in stats.tokens:
            print(f"  req {uid} += {tok}")
        for uid in stats.finished:
            print(f"  req {uid} done")
    print()
    for req in eng.sched.drain_finished().values():
        print(f"req {req.uid}: prompt={list(req.prompt)}")
        print(f"        -> {req.generated}  ({req.finish_reason})")

    # request-level telemetry the engine recorded along the way
    # (docs/observability.md; scrape-able via obs.serve_http)
    reg = eng.registry
    ms = lambda s: f"{s * 1e3:.1f}ms"  # noqa: E731
    ttft, tpot = reg.get("serve_ttft_seconds"), reg.get("serve_tpot_seconds")
    print(f"\ntelemetry: ttft p50={ms(ttft.percentile(0.5))} "
          f"p99={ms(ttft.percentile(0.99))}  "
          f"tpot p50={ms(tpot.percentile(0.5))}  "
          f"tokens={int(reg.get('serve_tokens_total').value)}")
    if not args.dense:
        # the paged cache's own surface (docs/serving.md "Paged KV")
        print(f"paged kv: block_size={args.block_size} "
              f"pool={eng.cache.num_blocks} blocks  "
              f"reuse_hits={int(reg.get('prefix_reuse_hits_total').value)}  "
              f"prefill_chunks={int(reg.get('prefill_chunks_total').value)}  "
              f"cow_copies={eng.alloc.cow_copies}")
        eng.drain()
        assert eng.alloc.blocks_free == eng.cache.num_blocks, \
            "block leak at shutdown"


if __name__ == "__main__":
    main()
